"""Incremental detokenization: O(1) amortised host time per token.

The naive streaming loop re-decodes the FULL generated id list after
every token — O(n^2) host time per stream on the step loop's critical
path (the reference's engines get vLLM's incremental detokenizer; this
is ours). Two wrinkles make "decode the new id and append" wrong:

- UTF-8: a multi-byte character can span tokens; its partial prefix
  decodes to U+FFFD until complete.
- Subword tokenizers: an id's text can depend on its neighbours
  (byte-level BPE byte joins, metaspace leading-space stripping), so
  `decode(a) + decode(b) != decode(a + b)` in general.

Strategy (the shape of vLLM's detokenize_incrementally): decode only a
bounded tail — a few already-committed CONTEXT ids plus the uncommitted
window — and splice the window's text after the committed text by
stripping the context's own rendering. The commit point only advances
when re-decoding with context reproduces the committed prefix exactly;
when a tokenizer ever violates that (context affects text at a distance
greater than CONTEXT), the step falls back to a full decode, so the
output is ALWAYS bit-identical to `tokenizer.decode(all_ids)` — parity
asserted per-step by tests over random streams."""

from __future__ import annotations

CONTEXT = 4   # committed ids re-decoded for boundary context
WINDOW = 16   # max uncommitted ids before the commit point advances
KEEP = 4      # uncommitted ids kept behind after an advance


class IncrementalDetokenizer:
    """Per-sequence streaming decoder.

    append(token_id) -> current full text (== decode(all ids so far)).
    """

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids: list[int] = []
        self._c = 0  # ids[:c] are committed
        self._committed = ""  # == decode(ids[:c])

    def append(self, token_id: int) -> str:
        self._ids.append(token_id)
        text = self._render()
        if len(self._ids) - self._c > WINDOW:
            self._advance()
        return text

    def current(self) -> str:
        return self._render()

    # -- internals ---------------------------------------------------------
    def _ctx_start(self) -> int:
        return max(0, self._c - CONTEXT)

    def _render(self) -> str:
        """committed + context-spliced tail; full decode on any doubt."""
        s = self._ctx_start()
        ctx_text = self._tok.decode(self._ids[s:self._c])
        tail = self._tok.decode(self._ids[s:])
        if tail.startswith(ctx_text):
            return self._committed + tail[len(ctx_text):]
        # context interacted with committed text at a distance — rare
        # (never for our byte/BPE tokenizers); correctness wins
        return self._tok.decode(self._ids)

    def _advance(self) -> None:
        """Move the commit point, keeping `_committed == decode(ids[:c])`.

        A candidate boundary is safe when the chunk's rendering is a
        prefix of the joint decode of everything pending — that holds
        for permanently-invalid bytes (their U+FFFD never changes) but
        not for a split mid-character (the joint decode renders the
        completed char differently). A UTF-8 char spans at most 4 bytes,
        so stepping the boundary back up to 4 ids always finds a safe
        cut; without this, a long invalid-byte run would grow the window
        unboundedly and regress to O(n^2) re-decoding."""
        s = self._ctx_start()
        ctx_text = self._tok.decode(self._ids[s:self._c])
        joint = self._tok.decode(self._ids[s:])
        target = len(self._ids) - KEEP
        for t in range(target, max(self._c, target - 4), -1):
            chunk = self._tok.decode(self._ids[s:t])
            if chunk.startswith(ctx_text) and joint.startswith(chunk):
                self._committed += chunk[len(ctx_text):]
                self._c = t
                return
        # No candidate cut within 4 ids was safe — a tokenizer violating
        # the CONTEXT-locality assumption could hit this on every append
        # and grow the uncommitted window without bound (back to the
        # O(n^2) behavior this module exists to avoid). Bound the window
        # with a forced full-decode commit; `_render` stays correct
        # because `_committed` equals decode(ids[:c]) by construction.
        if len(self._ids) - self._c > 4 * WINDOW:
            self._committed = self._tok.decode(self._ids[:target])
            self._c = target
