"""Tokenizer abstraction: HF tokenizers for real checkpoints, a byte-level
tokenizer for hermetic tests/benchmarks (no network, matching the reference's
practice of testing with tiny stand-in models — reference:
.github/workflows/router-e2e-test.yml uses facebook/opt-125m).
"""

from __future__ import annotations

import os
from typing import Protocol

_DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message['role'] }}|>\n{{ message['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


class Tokenizer(Protocol):
    eos_token_id: int | None
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, token_ids: list[int]) -> str: ...
    def apply_chat_template(self, messages: list[dict]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer with BOS/EOS specials. Hermetic, vocab 384."""

    BOS = 256
    EOS = 257

    def __init__(self):
        self.eos_token_id = self.EOS
        self.bos_token_id = self.BOS
        self.vocab_size = 384

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] + ids) if add_bos else ids

    def decode(self, token_ids: list[int]) -> str:
        data = bytes(t for t in token_ids if 0 <= t < 256)
        return data.decode("utf-8", errors="replace")

    def token_strings(self) -> list[str]:
        """Per-token strings for constrained decoding (structured.py):
        byte ids render alone; specials/unused ids are never forced."""
        return [
            bytes([i]).decode("utf-8", errors="replace") if i < 256 else ""
            for i in range(self.vocab_size)
        ]

    def apply_chat_template(self, messages: list[dict]) -> str:
        override = getattr(self, "chat_template_override", None)
        if override is not None:
            return _render_template(override, messages)
        parts = [f"<|{m['role']}|>\n{m['content']}\n" for m in messages]
        parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """Wrapper over a local HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            path, local_files_only=True
        )
        self.eos_token_id = self._tok.eos_token_id
        self.bos_token_id = getattr(self._tok, "bos_token_id", None)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, token_ids: list[int]) -> str:
        return self._tok.decode(token_ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        override = getattr(self, "chat_template_override", None)
        if override is not None:
            return _render_template(override, messages)
        if self._tok.chat_template is not None:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        return _render_template(_DEFAULT_CHAT_TEMPLATE, messages)


def _render_template(template: str, messages: list[dict]) -> str:
    import jinja2

    return jinja2.Template(template).render(
        messages=messages, add_generation_prompt=True
    )


def get_tokenizer(
    spec: str | None, model: str, chat_template: str | None = None
) -> Tokenizer:
    """Resolve the tokenizer.

    - explicit "byte" -> hermetic ByteTokenizer
    - explicit path (``spec``) -> must load, else raise (a silent fallback
      would serve garbage tokens against real weights)
    - no spec: the model dir if it is one, else (weight-free preset) the
      ByteTokenizer with a log line.

    ``chat_template``: optional Jinja override (a template string, or a
    path to a file containing one) applied over whatever the tokenizer
    ships — the ``--chat-template`` serving knob (reference capability:
    helm/values.yaml ``chatTemplate`` per modelSpec).
    """
    from production_stack_tpu.utils import init_logger

    logger = init_logger(__name__)
    explicit = spec is not None
    spec = spec or model
    if spec == "byte":
        tok: Tokenizer = ByteTokenizer()
    elif os.path.isdir(spec):
        tok = HFTokenizer(spec)  # raises on a broken checkpoint dir
    elif explicit:
        raise ValueError(
            f"tokenizer path {spec!r} does not exist; pass 'byte' for the "
            "hermetic byte tokenizer"
        )
    else:
        logger.info(
            "model %r is a weight-free preset; using the hermetic byte "
            "tokenizer", model,
        )
        tok = ByteTokenizer()
    if chat_template:
        if os.path.isfile(chat_template):
            with open(chat_template) as f:
                chat_template = f.read()
        elif "{" not in chat_template:
            # path-looking string (no Jinja syntax) whose file is
            # missing: rendering it verbatim would silently corrupt
            # every chat prompt — fail at startup instead
            raise ValueError(
                f"chat template file {chat_template!r} does not exist "
                "(an inline template must contain Jinja '{{ ... }}' "
                "syntax)"
            )
        tok.chat_template_override = chat_template  # type: ignore[union-attr]
    return tok
