"""Model runner: owns params + KV cache on device, dispatches jitted steps.

XLA-first batching contract (the piece that makes continuous batching work on
TPU without per-step recompilation):

- every device program has a **static shape**, selected from a small set of
  buckets; jit traces each bucket once and the compile cache does the rest;
- prefill packs chunks from up to max_prefill_seqs sequences into one
  dispatch (prefill_batch; group size bucketed to a power of two), each
  chunk padded to a power-of-two length bucket and the context padded to
  a whole-block bucket; single-sequence prefill keeps its own buckets;
- decode runs a fixed number of lanes (max_num_seqs) with the context padded
  to the max bucket needed this step; idle lanes point at the null block and
  their writes land in the reserved trash slot 0;
- KV caches are donated into every step, so XLA performs scatter updates
  in place in HBM (no cache copies).

The attention inner op is chosen at construction: the XLA gather path
(ops/attention.py) everywhere, or the Pallas kernel on TPU.
"""

from __future__ import annotations

import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.models import llama
from production_stack_tpu.models.config import ModelConfig
from production_stack_tpu.ops import attention as xla_attn
from production_stack_tpu.parallel import sharding as sharding_rules
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


def next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


# lane-type codes in the ragged pack's lane-meta header (lane_types /
# lane_lens / lane_budgets — the per-lane fields extending
# _decode_pack_layout to a lane-typed prefill+decode round). The device
# reads lane_types to pin idle prefill lanes' sampled slot to
# sampler.RAGGED_IDLE_TOKEN; lens/budgets make the buffer
# self-describing (chunk length / this round's K, remaining prompt /
# remaining token budget).
RAGGED_LANE_IDLE = 0
RAGGED_LANE_PREFILL = 1
RAGGED_LANE_DECODE = 2

# Row-block height of the unified ragged kernel's flattened query-row
# space (ops/pallas_attention.RAGGED_TQ). Prefill lanes pack their
# chunk rows RAGGED_TQ-aligned; decode lanes contribute one row each
# and share blocks.
RAGGED_TQ = 8


def _ceil_tq(n: int) -> int:
    return -(-n // RAGGED_TQ) * RAGGED_TQ


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        params: dict | None = None,
        mesh: jax.sharding.Mesh | None = None,
    ):
        self.config = config
        self.model_config: ModelConfig = config.model_config()
        self.dtype = jnp.dtype(config.dtype)
        self.cache_dtype = jnp.dtype(config.cache_dtype)
        self.max_model_len = config.resolved_max_model_len()

        mc = self.model_config
        if mc.is_moe and mc.moe_capacity_factor > 0:
            # serving steps pad decode lanes / prefill buckets, and the
            # GShard capacity path has no per-row validity inside
            # llama.forward — padded rows would steal expert capacity
            # from real tokens (ops/moe.py:moe_capacity). Serving always
            # uses the exact dense path; the capacity path is for
            # offline/bulk callers that manage their own padding.
            raise ValueError(
                f"model {mc.name}: moe_capacity_factor="
                f"{mc.moe_capacity_factor} is not servable; the engine "
                "requires the exact dense MoE path (capacity_factor=0)"
            )
        if not (1 <= config.num_scheduler_steps <= config.block_size):
            # validate at boot: a mid-serving ValueError from decode_multi
            # would kill the engine step-loop thread and hang every
            # in-flight request instead of failing fast here
            raise ValueError(
                f"num_scheduler_steps={config.num_scheduler_steps} must "
                f"be in [1, block_size={config.block_size}] (idle decode "
                "lanes park inside the trash block)"
            )
        tp = config.tensor_parallel_size
        pp = config.pipeline_parallel_size
        if mesh is None and (tp > 1 or pp > 1):
            mesh = sharding_rules.make_serving_mesh(tp, pp)
        self.mesh = mesh
        if self.mesh is not None:
            sharding_rules.validate_tp(mc, tp if pp > 1 else self.mesh.size)
        # forward implementation: the plain layer scan, or the
        # pipeline-staged phase loop when layers shard over pp
        if pp > 1:
            from production_stack_tpu.parallel import pp_serving

            pp_serving.validate_pp_serving(mc, pp, config)
            if config.attention_impl == "pallas":
                raise ValueError(
                    "attention_impl=pallas does not compose with "
                    "pipeline_parallel_size>1 yet (the kernels' own "
                    "shard_map cannot nest in the pp manual region); "
                    "use auto or xla"
                )
            self._forward = functools.partial(
                pp_serving.forward_pp, mesh=self.mesh
            )
        else:
            self._forward = llama.forward

        if params is None:
            # real checkpoints load from disk (local dir or HF cache);
            # preset/debug names fall through to random init
            from production_stack_tpu.models import weights as weight_loader

            # the mesh-sharding elif below handles TP placement for
            # loaded params, same as caller-supplied ones
            params = weight_loader.maybe_load(config.model, mc, self.dtype)
        if params is None:
            logger.info(
                "initializing random %s params (%.2fB params, %s, "
                "tp=%d, pp=%d)",
                mc.name, mc.num_params() / 1e9, config.dtype, tp, pp,
            )
            init_fn = lambda key: llama.init_params(mc, key, self.dtype)
            if self.mesh is not None:
                # init directly into the TP layout: no transient replicated
                # copy of the full weights on any single chip
                init_fn = jax.jit(
                    init_fn,
                    out_shardings=sharding_rules.param_shardings(
                        self.mesh, mc
                    ),
                )
            params = init_fn(jax.random.key(config.seed))
        elif self.mesh is not None:
            params = sharding_rules.shard_params(params, self.mesh, mc)
        self.params = params

        self.num_blocks = self._resolve_num_blocks()
        self.block_size = config.block_size
        num_slots = self.num_blocks * self.block_size
        # head-major (L, nkv, slots, d): the layout the Pallas kernels
        # and the MXU want (see ops/pallas_attention.py docstring)
        cache_shape = (
            mc.num_layers, mc.num_kv_heads, num_slots, mc.head_dim
        )
        logger.info(
            "allocating KV cache: %d blocks x %d slots (%.2f GiB)",
            self.num_blocks, self.block_size,
            2 * math.prod(cache_shape) * self.cache_dtype.itemsize / 2**30,
        )
        zeros = lambda: jnp.zeros(cache_shape, self.cache_dtype)
        if self.mesh is not None:
            zeros = jax.jit(
                zeros,
                out_shardings=sharding_rules.cache_sharding(self.mesh),
            )
        self.k_cache = zeros()
        self.v_cache = zeros()

        self._scale = mc.head_dim**-0.5
        # attention impl: pallas paged kernel on TPU; under TP the kernel
        # is shard_mapped over the kv-head-sharded cache (each chip's GQA
        # groups are local, so the kernel body needs no collectives)
        impl = config.attention_impl
        if impl == "auto":
            impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        if config.pipeline_parallel_size > 1:
            impl = "xla"  # see the pp validation above
        if impl not in ("xla", "pallas"):
            raise ValueError(
                f"attention_impl must be auto|xla|pallas, got {impl!r}"
            )
        if impl == "pallas" and jax.default_backend() == "tpu" and (
            mc.head_dim % 128
        ):
            # Mosaic requires DMA slices aligned to the (8, 128) lane
            # tiling: a head_dim below 128 (e.g. Llama-3.2-1B's 64) pads
            # the cache's lane dim and every page slice becomes a partial
            # tile ("must be aligned to tiling (128)" compile error).
            # Standard TPU-serving constraint; the XLA path serves these.
            logger.warning(
                "pallas attention requires head_dim %% 128 == 0 (got %d);"
                " using the XLA gather path", mc.head_dim,
            )
            impl = "xla"
        # sliding-window models (Phi-3-mini, Mistral-v0.1) ride the
        # pallas kernels too: the page walk starts at the window's first
        # page and masks within the boundary page (the smoke test below
        # compiles the windowed variant on hardware before committing)
        ragged_smoke_ok = True
        if impl == "pallas" and jax.default_backend() == "tpu":
            # compile-check the kernels on tiny shapes before
            # committing: if this TPU generation/toolchain rejects the
            # composed kernels, serve on the XLA path instead of
            # failing at the first request
            try:
                self._pallas_smoke_test(mc)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "pallas attention failed its smoke test (%s); "
                    "falling back to the XLA gather path", e,
                )
                impl = "xla"
            if impl == "pallas" and config.ragged_kernel:
                # the unified kernel degrades INDEPENDENTLY: a chip
                # that compiles the composed kernels but rejects the
                # CSR ragged grid keeps serving on pallas with
                # per-lane composition, not the slow XLA path
                try:
                    self._ragged_smoke_test(mc)
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "ragged paged-attention kernel failed its "
                        "smoke test (%s); composing the per-lane "
                        "kernels instead", e,
                    )
                    ragged_smoke_ok = False
        self.attention_impl = impl
        # single-kernel ragged paged attention: route EVERY pallas
        # attention call — decode rounds, packed prefill groups, mixed
        # lane-typed rounds — through the one batched-grid
        # ragged_paged_attention kernel (ops/pallas_attention.py), so
        # any lane mix is one launch and the packed-prefill/ragged
        # program variants key on padded ROW-count buckets instead of
        # the (s_pad, t_pad) lane-mix grid. --no-ragged-kernel keeps
        # the composed per-lane kernels as the A/B control.
        self.ragged_kernel = (
            bool(config.ragged_kernel) and impl == "pallas"
            and ragged_smoke_ok
        )
        logger.info(
            "attention impl: %s%s", impl,
            " (ragged kernel)" if self.ragged_kernel else "",
        )

        # multi-LoRA: stacked adapter buffers applied inside the jitted
        # steps (engine/lora.py); None when --enable-lora is off so the
        # step functions trace without the adapter math
        self.lora_manager = None
        if config.enable_lora:
            from production_stack_tpu.engine.lora import LoraManager

            self.lora_manager = LoraManager(
                mc, config.max_loras, config.max_lora_rank, self.dtype
            )
        # multi-host SPMD: logits must come back fully replicated so host 0
        # can pull them to the host for sampling (shards on follower hosts
        # are not addressable from host 0)
        self.replicate_logits = bool(config.multihost)

        # pipelined prefill (one packed h2d buffer per dispatch +
        # staged uploads): program variants take the fused buffer.
        # Single-device only: the fused-buffer transport targets the
        # tunneled single-chip link, and under a pp/tp mesh the packed
        # operand's inferred sharding trips SPMD partitioning (observed:
        # "PartitionId instruction is not supported" under pp x tp) —
        # meshed engines keep the per-array upload path
        self.prefill_pipeline = (
            bool(config.prefill_pipeline) and self.mesh is None
        )
        # per-phase prefill wall time (seconds) + dispatch counts, fed
        # to /metrics and the bench attribution slots: prep = host array
        # build, h2d = upload enqueue (staged uploads overlap compute
        # but still count — they are real link work), dispatch = jitted
        # call enqueue, fetch = device->host reads (engine-side)
        self.prefill_phase_s = {
            "prep": 0.0, "h2d": 0.0, "dispatch": 0.0, "fetch": 0.0,
        }
        self.prefill_phase_n = {
            "prep": 0, "h2d": 0, "dispatch": 0, "fetch": 0,
        }

        # jit caches keyed by bucket tuple
        self._prefill_fns: dict[tuple[int, int], object] = {}
        self._verify_batch_fns: dict[tuple[int, int, int], object] = {}
        self._prefill_batch_fns: dict[tuple[int, int, int], object] = {}
        self._decode_fns: dict[tuple[int, int], object] = {}
        self._decode_multi_fns: dict[tuple[int, int, int], object] = {}
        # unified ragged rounds, keyed by (s_pad, t_pad, pc_pad, b,
        # c_pad, k, flags...) — see ragged_dispatch
        self._ragged_fns: dict[tuple, object] = {}
        self._embed_fns: dict[tuple[int, int], object] = {}
        # donated in-place KV block scatter (offload restore / PD
        # import), keyed by (n_src_pad, n_dst_pad) pow2 buckets
        self._import_fns: dict[tuple[int, int], object] = {}

        # compile-count observability: every program-variant build (a
        # jit-cache miss on one of the builders above) is counted per
        # kind — the chip-window cold-start tax and the ragged-kernel
        # variant-space shrink become measurable (tpu:compile_events_
        # total, bench `compiles` slot) instead of inferred from logs
        self.compile_events: dict[str, int] = {}
        self.compile_events_total = 0

        self.max_ctx_bucket = self._ctx_bucket(self.max_model_len)

    def _note_compile(self, kind: str) -> None:
        """Count one program-variant build (jit cache miss)."""
        self.compile_events[kind] = self.compile_events.get(kind, 0) + 1
        self.compile_events_total += 1

    # -- sizing -----------------------------------------------------------
    def _resolve_num_blocks(self) -> int:
        cfg, mc = self.config, self.model_config
        if cfg.num_kv_blocks is not None:
            return cfg.num_kv_blocks
        bytes_per_block = (
            2
            * mc.num_layers
            * cfg.block_size
            * mc.num_kv_heads
            * mc.head_dim
            * self.cache_dtype.itemsize
        )
        tp = self.mesh.size if self.mesh is not None else 1
        # per-chip view: weights and KV blocks are both split ~1/tp.
        param_bytes = mc.num_params() * self.dtype.itemsize // tp
        try:
            stats = jax.devices()[0].memory_stats() or {}
        except Exception as e:
            logger.debug("memory_stats unavailable (%s); using estimate", e)
            stats = {}
        if "bytes_limit" in stats:
            limit = stats["bytes_limit"]
            # caller-supplied params may still be host arrays at this point
            # (server.py passes numpy); bytes_in_use then misses them, so
            # reserve at least the weight estimate either way.
            reserved = max(stats.get("bytes_in_use", 0), param_bytes)
        else:
            limit = 16 * 2**30
            reserved = param_bytes
        budget = int(limit * cfg.hbm_utilization) - reserved
        num = max(2, budget // (bytes_per_block // tp))
        # cap: no point holding more than max_model_len * max_num_seqs * 2
        cap = (
            2
            * (self.max_model_len // cfg.block_size + 1)
            * max(1, cfg.max_num_seqs)
        )
        return int(min(num, max(cap, 2)))

    def _pallas_smoke_test(self, mc: ModelConfig) -> None:
        bs = self.block_size
        d, nkv = mc.head_dim, mc.num_kv_heads
        # probe the exact kernel variants serving will compile — the
        # windowed page walk included (traced loop start + guarded
        # DMA); `_attn` routes through the shard_map TP wrappers under
        # a mesh, exactly as the step builders do
        kc = jnp.zeros((1, nkv, 4 * bs, d), self.cache_dtype)
        q = jnp.zeros((1, mc.num_heads, d), self.dtype)
        tables = jnp.zeros((1, 2), jnp.int32)
        lens = jnp.ones((1,), jnp.int32)
        qp = jnp.zeros((8, mc.num_heads, d), self.dtype)
        table1 = jnp.zeros((2,), jnp.int32)
        if self.mesh is not None:
            # exercise the exact shard_map paths serving will take
            kc = jax.device_put(
                kc, sharding_rules.cache_sharding(self.mesh)
            )
        out = self._attn("decode", q, jnp.int32(0), kc, kc, tables,
                         lens)
        out2 = self._attn("prefill", qp, jnp.int32(0), kc, kc, table1,
                          jnp.int32(0))
        jax.block_until_ready((out, out2))

    def _ragged_smoke_test(self, mc: ModelConfig) -> None:
        """Probe the unified ragged kernel in the grid shape serving
        dispatches — one prefill q-tile beside one decode row — so a
        toolchain that rejects the CSR scalar-prefetch grid degrades
        to the composed kernels, not the XLA path."""
        bs = self.block_size
        d, nkv = mc.head_dim, mc.num_kv_heads
        kc = jnp.zeros((1, nkv, 4 * bs, d), self.cache_dtype)
        if self.mesh is not None:
            kc = jax.device_put(
                kc, sharding_rules.cache_sharding(self.mesh)
            )
        blk_seg = jnp.asarray([0, 1, 2], jnp.int32)
        seg_meta = jnp.asarray(
            [[0, 0, RAGGED_TQ, 0], [1, 0, 1, 0]], jnp.int32
        )
        qr = jnp.zeros((2 * RAGGED_TQ, mc.num_heads, d), self.dtype)
        out = self._attn(
            "ragged", qr, jnp.int32(0), kc, kc,
            jnp.zeros((2, 2), jnp.int32), blk_seg, seg_meta,
        )
        jax.block_until_ready(out)

    def _step_jit_kwargs(self, n_host_outs: int = 1) -> dict:
        """Extra jit options for the prefill/decode step builders.
        `n_host_outs` = leading outputs host 0 may fetch (replicated
        under multihost so followers' shards are never addressed)."""
        if not (self.replicate_logits and self.mesh is not None):
            return {}
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(self.mesh, PartitionSpec())
        cs = sharding_rules.cache_sharding(self.mesh)
        return {"out_shardings": (rep,) * n_host_outs + (cs, cs)}

    # -- buckets ----------------------------------------------------------
    def _ctx_bucket(self, num_tokens: int) -> int:
        """Context bucket in tokens: whole blocks, pow2 block count."""
        blocks = max(1, -(-num_tokens // self.block_size))
        blocks = next_pow2(blocks)
        max_blocks = -(-self.max_model_len // self.block_size)
        return min(blocks, next_pow2(max_blocks)) * self.block_size

    def _prefill_bucket(self, chunk_len: int) -> int:
        return min(
            next_pow2(max(chunk_len, 8)),
            next_pow2(self.config.max_prefill_chunk),
        )

    def _pin_cache_layout(self, kc, vc):
        """Pin the KV caches to the row-major physical layout the Pallas
        custom calls constrain their operands to.

        Without this, XLA may pick a different layout for the scan body's
        scatter (observed on v5e: {3,1,2,0} vs the kernel's {3,2,1,0})
        and insert a FULL-CACHE layout-conversion copy per step — 2 x
        3.8 GiB per step for the 3B model, which OOMed HBM outright."""
        if self.attention_impl != "pallas" or (
            jax.default_backend() != "tpu"
        ):
            return kc, vc
        from jax.experimental.layout import Layout, with_layout_constraint

        fmt = Layout((0, 1, 2, 3))
        return (with_layout_constraint(kc, fmt),
                with_layout_constraint(vc, fmt))

    # -- jitted step builders ---------------------------------------------
    # stackcheck: hot-path — the ONE dispatch seam every pallas
    # attention call goes through (trace-time only: closed over by the
    # jitted step builders); collapses the former per-site
    # `mesh is not None -> *_tp else *` call ladders
    def _attn(self, kind: str, q, layer, kc, vc, *args):
        """Route one attention call to the pallas kernel for `kind`
        ("prefill" | "decode" | "ragged"), picking the shard_map TP
        variant under a mesh and filling the static block-size/scale/
        interpret/window arguments from the runner's config. All
        kernel call sites dispatch through here, so a new kernel (the
        unified ragged one) lands at one seam instead of eight."""
        from production_stack_tpu.ops import pallas_attention

        fns = {
            "prefill": (
                pallas_attention.paged_prefill_attention,
                pallas_attention.paged_prefill_attention_tp,
            ),
            "decode": (
                pallas_attention.paged_decode_attention,
                pallas_attention.paged_decode_attention_tp,
            ),
            "ragged": (
                pallas_attention.ragged_paged_attention,
                pallas_attention.ragged_paged_attention_tp,
            ),
        }[kind]
        pallas_attention._note_trace(kind)  # launch accounting
        kw = dict(
            block_size=self.block_size,
            scale=self._scale,
            interpret=jax.default_backend() != "tpu",
            window=self.model_config.sliding_window,
        )
        if self.mesh is not None:
            return fns[1](q, kc, vc, layer, *args, mesh=self.mesh, **kw)
        return fns[0](q, kc, vc, layer, *args, **kw)

    def _prefill_attn_closure(self):
        """The per-layer attention callback shared by the prefill and
        verify step builders (pallas paged kernel or XLA gather path).

        `gather_slots` = this sequence's padded block table (P,) on the
        pallas path (the kernel streams context pages from HBM once per
        chunk — the per-layer (ctx, nkv, d) gathered copy is never
        built; q row 0 is always a real token, so positions[0] is the
        chunk's absolute start position), or the flat slot gather on the
        XLA path."""
        scale = self._scale
        if self.attention_impl == "pallas":

            def attn(q, l, kc, vc, gather_slots, q_positions, total_len):
                return self._attn(
                    "prefill", q, l, kc, vc, gather_slots,
                    q_positions[0],
                )
        else:

            window = self.model_config.sliding_window

            def attn(q, l, kc, vc, gather_slots, q_positions, total_len):
                # head-major cache + traced `l`: [l, :, slots] has two
                # advanced indices split by a slice, so numpy hoists them
                # to the front — the result is ALREADY (c, nkv, d)
                k_ctx = kc[l, :, gather_slots]
                v_ctx = vc[l, :, gather_slots]
                return xla_attn.context_attention_prefill(
                    q, k_ctx, v_ctx, q_positions, total_len, scale,
                    window=window,
                )

        return attn

    def _prefill_host_prep(
        self, token_ids: list[int], block_table: list[int],
        start_pos: int, total_len: int,
    ):
        """Shared host-side argument prep for prefill/verify dispatches:
        (tokens, positions_dev, write_slots, gather_slots, t_pad, c_pad).
        Padded rows carry position -1 -> rope of 0, write to trash."""
        t = len(token_ids)
        t_pad = self._prefill_bucket(t)
        c_pad = self._ctx_bucket(total_len)
        tokens = np.zeros((t_pad,), dtype=np.int32)
        tokens[:t] = token_ids
        positions = np.full((t_pad,), -1, dtype=np.int32)
        positions[:t] = np.arange(start_pos, start_pos + t)
        write_slots = self._slots_for_positions(block_table, positions)
        positions_dev = np.where(positions < 0, 0, positions).astype(
            np.int32
        )
        if self.attention_impl == "pallas":
            gather_slots = self._padded_block_table(
                block_table, c_pad // self.block_size
            )
        else:
            gather_slots = self._gather_slots_for_table(block_table, c_pad)
        return tokens, positions_dev, write_slots, gather_slots, t_pad, c_pad

    # -- pipelined prefill: fused h2d buffer --------------------------------
    def _phase_add(self, name: str, dt: float) -> None:
        self.prefill_phase_s[name] += dt
        self.prefill_phase_n[name] += 1

    # -- per-dispatch phase attribution (request timelines) -----------------
    # A snapshot/delta pair around one dispatch attributes its prep/h2d/
    # dispatch/fetch wall time to the requests it served (the engine's
    # prefill_chunk timeline events). Pure host dict copies: no device
    # handle is touched, so the marked hot paths stay sync-free.
    def phase_snapshot(self) -> dict[str, float]:
        return dict(self.prefill_phase_s)

    def phase_delta(self, snapshot: dict[str, float]) -> dict[str, float]:
        return {
            k: round(v - snapshot.get(k, 0.0), 6)
            for k, v in self.prefill_phase_s.items()
            if v - snapshot.get(k, 0.0) > 0.0
        }

    @staticmethod
    def _layout_of(fields: list[tuple[str, tuple[int, ...]]]):
        layout: dict[str, tuple[int, tuple[int, ...]]] = {}
        off = 0
        for name, shape in fields:
            layout[name] = (off, shape)
            off += int(np.prod(shape))
        return layout, off

    def _prefill_pack_layout(self, t_pad: int, c_pad: int,
                             want_plp: bool = False):
        """Static layout of the ONE int32 host->device buffer a
        single-sequence prefill dispatch ships (mirror of
        _decode_pack_layout: through a tunneled chip every separate
        buffer creation pays link latency, so the ~8 small per-dispatch
        arrays fuse into one transfer; f32/u32 fields travel bitcast)."""
        g_shape = (
            (c_pad // self.block_size,)
            if self.attention_impl == "pallas" else (c_pad,)
        )
        fields = [
            ("tokens", (t_pad,)),
            ("positions", (t_pad,)),
            ("write_slots", (t_pad,)),
            ("gather_slots", g_shape),
            ("total_len", (1,)),
            ("last_row", (1,)),
            ("temps", (1,)),
            ("top_ps", (1,)),
            ("top_ks", (1,)),
            ("min_ps", (1,)),
            ("keys", (1, 2)),
        ]
        if want_plp:
            fields.append(("targets", (t_pad,)))
        return self._layout_of(fields)

    def _packed_prefill_pack_layout(self, s_pad: int, t_pad: int,
                                    c_pad: int):
        """Packed cross-sequence variant of _prefill_pack_layout."""
        tab_shape = (
            (s_pad, c_pad // self.block_size)
            if self.attention_impl == "pallas" else (s_pad, c_pad)
        )
        fields = [
            ("tokens", (s_pad * t_pad,)),
            ("positions", (s_pad * t_pad,)),
            ("write_slots", (s_pad * t_pad,)),
            ("tables", tab_shape),
            ("q_starts", (s_pad,)),
            ("total_lens", (s_pad,)),
            ("last_rows", (s_pad,)),
            ("temps", (s_pad,)),
            ("top_ps", (s_pad,)),
            ("top_ks", (s_pad,)),
            ("min_ps", (s_pad,)),
            ("keys", (s_pad, 2)),
        ]
        return self._layout_of(fields)

    # -- ragged-rows prefill pack (single-kernel mode) ---------------------
    # Under the unified ragged kernel the packed-prefill token axis is
    # RAGGED: each lane's chunk rows pack back-to-back (RAGGED_TQ-
    # aligned) with lane offsets riding per-lane metadata instead of a
    # per-lane t_pad shape — so the program variant keys on the padded
    # ROW bucket (r_pad, pc_pad), not the (s_pad, t_pad) lane-mix
    # pair, and the precompile grid collapses accordingly.
    def _rows_lane_cap(self) -> int:
        """Static prefill-lane capacity of the ragged-rows programs
        (config-derived, NOT part of the program key)."""
        return next_pow2(max(self.config.max_prefill_seqs, 1))

    def _rows_bucket(self, n_rows: int) -> int:
        return next_pow2(max(n_rows, RAGGED_TQ))

    def _rows_dims(
        self, chunks: list[list[int]], total_lens: list[int]
    ) -> tuple[int, int]:
        """(r_pad, pc_pad) row/context buckets for a ragged-rows
        prefill group."""
        r_pad = self._rows_bucket(
            sum(_ceil_tq(len(c)) for c in chunks)
        )
        pc_pad = max(self._ctx_bucket(tl) for tl in total_lens)
        return r_pad, pc_pad

    def _rows_prefill_pack_layout(self, r_pad: int, pc_pad: int):
        """Ragged-rows variant of _packed_prefill_pack_layout: flat
        row-axis fields + per-lane metadata at the static lane cap."""
        s_cap = self._rows_lane_cap()
        fields = [
            ("tokens", (r_pad,)),
            ("positions", (r_pad,)),
            ("write_slots", (r_pad,)),
            ("tables", (s_cap, pc_pad // self.block_size)),
            ("lane_row0", (s_cap,)),
            ("lane_rows", (s_cap,)),
            ("q_starts", (s_cap,)),
            ("last_rows", (s_cap,)),
            ("temps", (s_cap,)),
            ("top_ps", (s_cap,)),
            ("top_ks", (s_cap,)),
            ("min_ps", (s_cap,)),
            ("keys", (s_cap, 2)),
        ]
        return self._layout_of(fields)

    # stackcheck: hot-path — host build of the ragged-rows prefill
    # pack (dispatch + staging prefetch); one pass over the lanes, no
    # device fetch
    def _fill_rows_prefill_pack(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
        sampling=None,
    ) -> tuple[int, int, np.ndarray]:
        """Host-side build of the ragged-rows prefill pack; returns
        (r_pad, pc_pad, packed). Lane i's chunk occupies rows
        [lane_row0[i], lane_row0[i] + len(chunk)) of the flat axis;
        the RAGGED_TQ-alignment tail rows and the bucket tail carry
        position -1 -> rope 0, write the trash slot, and are never
        stored by the kernel's causal rows (same padded-row contract
        as the composed pack)."""
        n = len(chunks)
        s_cap = self._rows_lane_cap()
        r_pad, pc_pad = self._rows_dims(chunks, total_lens)
        n_pages = pc_pad // self.block_size
        tokens = np.zeros((r_pad,), np.int32)
        positions = np.full((r_pad,), -1, np.int32)
        write_slots = np.zeros((r_pad,), np.int32)
        tables = np.zeros((s_cap, n_pages), np.int32)
        lane_row0 = np.zeros((s_cap,), np.int32)
        lane_rows = np.zeros((s_cap,), np.int32)
        q_starts = np.zeros((s_cap,), np.int32)
        last_rows = np.zeros((s_cap,), np.int32)
        row = 0
        for i, (ids, start) in enumerate(zip(chunks, start_positions)):
            t = len(ids)
            tokens[row: row + t] = ids
            pos = np.arange(start, start + t, dtype=np.int32)
            positions[row: row + t] = pos
            write_slots[row: row + t] = self._slots_for_positions(
                block_tables[i], pos
            )
            tables[i] = self._padded_block_table(
                block_tables[i], n_pages
            )
            lane_row0[i] = row
            lane_rows[i] = _ceil_tq(t)
            q_starts[i] = start
            last_rows[i] = row + t - 1
            row += _ceil_tq(t)
        # idle lanes: empty row ranges past the packed region (cover
        # nothing in the in-trace block map), last row 0 (sampled slot
        # pinned to the idle sentinel by the step)
        lane_row0[n:] = row
        positions_dev = np.where(positions < 0, 0, positions).astype(
            np.int32
        )
        layout, size = self._rows_prefill_pack_layout(r_pad, pc_pad)
        packed = np.zeros((size,), np.int32)
        put = functools.partial(self._pack_put, packed, layout)
        put("tokens", tokens)
        put("positions", positions_dev)
        put("write_slots", write_slots)
        put("tables", tables)
        put("lane_row0", lane_row0)
        put("lane_rows", lane_rows)
        put("q_starts", q_starts)
        put("last_rows", last_rows)
        temps, top_ps, top_ks, min_ps, keys = self._sampling_args(
            s_cap, sampling
        )
        put("temps", temps)
        put("top_ps", top_ps)
        put("top_ks", top_ks)
        put("min_ps", min_ps)
        put("keys", keys)
        return r_pad, pc_pad, packed

    def _rows_pf_seg_meta(self, r_pad, lane_row0, lane_rows, q_starts):
        """In-trace per-block segment metadata for the ragged-rows
        prefill region: every RAGGED_TQ block belongs to at most one
        lane (lanes pack TQ-aligned), so each block carries one
        segment — [lane, 0, TQ, q_pos of the block's first row] — and
        blocks outside every lane carry a zero-row segment the kernel
        walks past for free."""
        tq = RAGGED_TQ
        n_blk = r_pad // tq
        blk0 = jnp.arange(n_blk, dtype=jnp.int32) * tq
        ends = lane_row0 + lane_rows
        cover = (
            (blk0[:, None] >= lane_row0[None, :])
            & (blk0[:, None] < ends[None, :])
        )
        has = jnp.any(cover, axis=1)
        lane_of = jnp.argmax(cover, axis=1).astype(jnp.int32)
        rows = jnp.where(has, tq, 0).astype(jnp.int32)
        qpos0 = jnp.where(
            has, q_starts[lane_of] + (blk0 - lane_row0[lane_of]), 0
        )
        return jnp.stack(
            [lane_of, jnp.zeros_like(blk0), rows, qpos0], axis=1
        )

    @staticmethod
    def _rows_slot_vector(
        chunks: list[list[int]], slots, r_pad: int
    ) -> np.ndarray:
        """Per-row LoRA slot vector over the ragged-rows flat axis —
        the ONE copy of the lane->row expansion, kept in lockstep with
        _fill_rows_prefill_pack's row packing (RAGGED_TQ-aligned lane
        starts)."""
        slots = slots if slots is not None else [0] * len(chunks)
        per_row = np.zeros((r_pad,), np.int32)
        row = 0
        for ids, slot in zip(chunks, slots):
            per_row[row: row + len(ids)] = slot
            row += _ceil_tq(len(ids))
        return per_row

    def _rows_lora_kwargs(
        self, lora_slots, chunks: list[list[int]], r_pad: int
    ) -> dict:
        """Ragged-rows mirror of _packed_lora_kwargs: uniform-adapter
        fast path, else a per-row slot vector over the flat axis."""
        if self.lora_manager is None:
            return {}
        slots = (
            lora_slots if lora_slots is not None else [0] * len(chunks)
        )
        if len(set(slots)) <= 1:
            slots_arg = jnp.int32(slots[0] if slots else 0)
        else:
            slots_arg = jnp.asarray(
                self._rows_slot_vector(chunks, slots, r_pad)
            )
        return {
            "lora": self.lora_manager.buffers,
            "lora_slots": slots_arg,
        }

    def _make_prefill_rows_step(self, r_pad: int, pc_pad: int):
        """Ragged-rows packed prefill step: chunks from up to
        max_prefill_seqs sequences pack back-to-back on ONE flat row
        axis and the whole group's chunk attention is ONE
        ragged_paged_attention launch — the un-jitted core shared by
        _build_prefill_rows (split prefill path) and the fused
        lane-typed round builder (_build_ragged_rows)."""
        mc = self.model_config
        from production_stack_tpu.engine.sampler import sample_tokens

        s_cap = self._rows_lane_cap()
        layout, _size = self._rows_prefill_pack_layout(r_pad, pc_pad)

        def _seg(packed, name, _lo=layout):
            return self._pack_seg(packed, _lo, name)

        def unpack(packed):
            def f32(name):
                return jax.lax.bitcast_convert_type(
                    _seg(packed, name), jnp.float32
                )

            return {
                "tokens": _seg(packed, "tokens"),
                "positions": _seg(packed, "positions"),
                "write_slots": _seg(packed, "write_slots"),
                "tables": _seg(packed, "tables"),
                "lane_row0": _seg(packed, "lane_row0"),
                "lane_rows": _seg(packed, "lane_rows"),
                "q_starts": _seg(packed, "q_starts"),
                "last_rows": _seg(packed, "last_rows"),
                "temps": f32("temps"),
                "top_ps": f32("top_ps"),
                "top_ks": _seg(packed, "top_ks"),
                "min_ps": f32("min_ps"),
                "keys": jax.lax.bitcast_convert_type(
                    _seg(packed, "keys"), jnp.uint32
                ),
            }

        def step(params, kc, vc, packed, lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            pf = unpack(packed)
            seg_meta = self._rows_pf_seg_meta(
                r_pad, pf["lane_row0"], pf["lane_rows"], pf["q_starts"]
            )
            blk_seg = jnp.arange(
                r_pad // RAGGED_TQ + 1, dtype=jnp.int32
            )

            def attn_fn(q, l, kcc, vcc):
                return self._attn(
                    "ragged", q, l, kcc, vcc, pf["tables"], blk_seg,
                    seg_meta,
                )

            logits, kc, vc = self._forward(
                mc, params, pf["tokens"], pf["positions"], kc, vc,
                pf["write_slots"], attn_fn,
                logits_rows=pf["last_rows"],
                lora=lora, lora_slots=lora_slots,
            )
            sampled = sample_tokens(
                logits, pf["temps"], pf["top_ps"], pf["top_ks"],
                pf["keys"], min_p=pf["min_ps"],
            )
            return sampled, logits, kc, vc

        step._unpack = unpack  # the fused-round builder reuses it
        return step

    def _build_prefill_rows(self, r_pad: int, pc_pad: int):
        """Jitted ragged-rows packed prefill (kernel-mode variant of
        _build_prefill_batch; program key (r_pad, pc_pad))."""
        return jax.jit(
            self._make_prefill_rows_step(r_pad, pc_pad),
            donate_argnums=(1, 2), **self._step_jit_kwargs(2),
        )

    @staticmethod
    def _pack_put(packed: np.ndarray, layout: dict, name: str,
                  arr: np.ndarray) -> None:
        off, shape = layout[name]
        n = int(np.prod(shape))
        packed[off:off + n] = np.asarray(arr).reshape(-1).view(np.int32)

    @staticmethod
    def _pack_seg(packed, layout: dict, name: str):
        """Device-side static-slice read of one packed-buffer field
        (the unpack mirror of _pack_put), shared by every fused-buffer
        step builder."""
        off, shape = layout[name]
        n = int(np.prod(shape))
        return packed[off:off + n].reshape(shape)

    def _fill_prefill_pack(
        self, token_ids: list[int], start_pos: int,
        block_table: list[int], total_len: int, sampling=None,
        prompt_lp_targets: list[int] | None = None,
    ) -> tuple[int, int, np.ndarray]:
        """Host-side build of the single-sequence prefill pack; returns
        (t_pad, c_pad, packed)."""
        t = len(token_ids)
        (tokens, positions_dev, write_slots, gather_slots,
         t_pad, c_pad) = self._prefill_host_prep(
            token_ids, block_table, start_pos, total_len
        )
        want_plp = prompt_lp_targets is not None
        layout, size = self._prefill_pack_layout(t_pad, c_pad, want_plp)
        packed = np.zeros((size,), np.int32)
        put = functools.partial(self._pack_put, packed, layout)
        put("tokens", tokens)
        put("positions", positions_dev)
        put("write_slots", write_slots)
        put("gather_slots", gather_slots)
        put("total_len", np.asarray([total_len], np.int32))
        put("last_row", np.asarray([t - 1], np.int32))
        temps, top_ps, top_ks, min_ps, keys = self._sampling_args(
            1, sampling
        )
        put("temps", temps)
        put("top_ps", top_ps)
        put("top_ks", top_ks)
        put("min_ps", min_ps)
        put("keys", keys)
        if want_plp:
            tg = np.full((t_pad,), -1, np.int32)
            tg[: len(prompt_lp_targets)] = prompt_lp_targets
            put("targets", tg)
        return t_pad, c_pad, packed

    def _fill_packed_prefill_pack(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
        sampling=None,
    ) -> tuple[int, int, int, np.ndarray]:
        """Host-side build of the packed cross-sequence prefill pack;
        returns (s_pad, t_pad, c_pad, packed)."""
        n = len(chunks)
        (s_pad, t_pad, c_pad, tokens, positions_dev, write_slots,
         q_starts, tl_full, tables) = self._packed_host_prep(
            chunks, start_positions, block_tables, total_lens
        )
        last_rows = np.zeros((s_pad,), dtype=np.int32)
        for s, ids in enumerate(chunks):
            last_rows[s] = s * t_pad + (len(ids) - 1)
        for s in range(n, s_pad):
            last_rows[s] = s * t_pad
        layout, size = self._packed_prefill_pack_layout(
            s_pad, t_pad, c_pad
        )
        packed = np.zeros((size,), np.int32)
        put = functools.partial(self._pack_put, packed, layout)
        put("tokens", tokens.reshape(-1))
        put("positions", positions_dev.reshape(-1))
        put("write_slots", write_slots.reshape(-1))
        put("tables", tables)
        put("q_starts", q_starts)
        put("total_lens", tl_full)
        put("last_rows", last_rows)
        temps, top_ps, top_ks, min_ps, keys = self._sampling_args(
            s_pad, sampling
        )
        put("temps", temps)
        put("top_ps", top_ps)
        put("top_ks", top_ks)
        put("min_ps", min_ps)
        put("keys", keys)
        return s_pad, t_pad, c_pad, packed

    # stackcheck: hot-path — staging must overlap the in-flight dispatch;
    # any hidden host-device sync here serializes the prefill pipeline
    def stage_prefill(
        self, token_ids: list[int], start_pos: int,
        block_table: list[int], total_len: int, sampling=None,
    ) -> tuple:
        """Speculative h2d prefetch for a FUTURE prefill chunk: build
        the packed buffer and START its async host->device transfer now
        so the upload overlaps the in-flight dispatch's compute instead
        of sitting serially before the next one (prefill mirror of
        stage_decode_multi). Returns a handle for prefill(staged=...);
        the caller (engine) validates its fingerprint before use."""
        t0 = time.perf_counter()
        t_pad, c_pad, packed = self._fill_prefill_pack(
            token_ids, start_pos, block_table, total_len,
            sampling=sampling,
        )
        t1 = time.perf_counter()
        self._phase_add("prep", t1 - t0)
        handle = (("single", t_pad, c_pad), jax.device_put(packed))
        self._phase_add("h2d", time.perf_counter() - t1)
        return handle

    # stackcheck: hot-path
    def stage_prefill_batch(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
        sampling=None,
    ) -> tuple:
        """Packed-group variant of stage_prefill."""
        t0 = time.perf_counter()
        if self.ragged_kernel and self.prefill_pipeline:
            r_pad, pc_pad, packed = self._fill_rows_prefill_pack(
                chunks, start_positions, block_tables, total_lens,
                sampling=sampling,
            )
            key = ("rows", r_pad, pc_pad)
        else:
            s_pad, t_pad, c_pad, packed = self._fill_packed_prefill_pack(
                chunks, start_positions, block_tables, total_lens,
                sampling=sampling,
            )
            key = ("packed", s_pad, t_pad, c_pad)
        t1 = time.perf_counter()
        self._phase_add("prep", t1 - t0)
        handle = (key, jax.device_put(packed))
        self._phase_add("h2d", time.perf_counter() - t1)
        return handle

    def _build_prefill(self, t_pad: int, c_pad: int,
                       want_prompt_lp: bool = False):
        mc = self.model_config
        from production_stack_tpu.engine.sampler import (
            sample_tokens,
            token_logprobs,
        )

        attn = self._prefill_attn_closure()

        def step(params, kc, vc, tokens, positions, write_slots,
                 gather_slots, total_len, last_row, temps, top_ps,
                 top_ks, min_ps, keys, targets=None,
                 lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            attn_fn = functools.partial(
                attn,
                gather_slots=gather_slots,
                q_positions=positions,
                total_len=total_len,
            )
            logits, kc, vc = self._forward(
                mc, params, tokens, positions, kc, vc, write_slots,
                lambda q, l, k, v: attn_fn(q, l, k, v),
                # prompt-logprobs needs every row's distribution; the
                # normal path materializes only the LAST row (the first
                # generated token's) to keep the program output small
                logits_rows=(
                    jnp.arange(t_pad) if want_prompt_lp
                    else last_row[None]
                ),
                lora=lora, lora_slots=lora_slots,
            )
            last_logits = logits[last_row] if want_prompt_lp else logits[0]
            # sample the first generated token ON DEVICE: the host then
            # fetches 4 bytes instead of a (vocab,) f32 row — the logit
            # fetch was the dominant per-prompt TTFT cost through
            # remote-attached chips (the logits output stays available
            # for penalty/debug paths, unfetched)
            token = sample_tokens(last_logits[None], temps, top_ps,
                                  top_ks, keys, min_p=min_ps)[0]
            if not want_prompt_lp:
                return token, last_logits, kc, vc
            # vLLM prompt_logprobs role, computed ON DEVICE: row i's
            # distribution scores prompt token i+1 (`targets`, -1 =
            # masked padding row). The host fetches (t_pad,) chosen +
            # (t_pad, CAP) alternatives — never (t_pad, vocab) rows.
            # Same extraction as generation logprobs (sampler.
            # token_logprobs), so the two stay semantics-identical.
            chosen, top_vals, top_ids = token_logprobs(
                logits, jnp.maximum(targets, 0)
            )
            chosen = jnp.where(targets >= 0, chosen, 0.0)
            return (token, last_logits, chosen, top_vals, top_ids,
                    kc, vc)

        jit_kw = self._step_jit_kwargs(2 if not want_prompt_lp else 5)
        if not self.prefill_pipeline:
            return jax.jit(step, donate_argnums=(1, 2), **jit_kw)

        # pipelined variant: ONE fused i32 operand instead of ~8 small
        # h2d transfers (layout shared with the host build,
        # _prefill_pack_layout); unpack on device then run the SAME step
        layout, _size = self._prefill_pack_layout(
            t_pad, c_pad, want_prompt_lp
        )

        def _seg(packed, name, _lo=layout):
            return self._pack_seg(packed, _lo, name)

        def packed_step(params, kc, vc, packed, lora=None,
                        lora_slots=None):
            def f32(name):
                return jax.lax.bitcast_convert_type(
                    _seg(packed, name), jnp.float32
                )

            plp_kw = (
                {"targets": _seg(packed, "targets")}
                if want_prompt_lp else {}
            )
            return step(
                params, kc, vc,
                _seg(packed, "tokens"),
                _seg(packed, "positions"),
                _seg(packed, "write_slots"),
                _seg(packed, "gather_slots"),
                _seg(packed, "total_len")[0],
                _seg(packed, "last_row")[0],
                f32("temps"), f32("top_ps"),
                _seg(packed, "top_ks"), f32("min_ps"),
                jax.lax.bitcast_convert_type(
                    _seg(packed, "keys"), jnp.uint32
                ),
                lora=lora, lora_slots=lora_slots,
                **plp_kw,
            )

        return jax.jit(packed_step, donate_argnums=(1, 2), **jit_kw)

    def _build_verify_batch(self, s_pad: int, t_pad: int, c_pad: int):
        """Batched speculative verification: s_pad lanes' draft chunks
        [last_token, d_1..d_k] run in ONE packed prefill-shaped forward,
        and EVERY row is sampled on device with its own PRNG key.

        Because the engine's sampling keys depend only on
        (seed, generated_len) — not on sampled history — row j of a lane
        samples with the exact key autoregressive step j would have
        used, so acceptance-by-equality yields outputs bit-identical to
        sequential sampling at any temperature (greedy rows reduce to
        argmax inside sample_tokens). The host fetches (s_pad*t_pad,)
        int32 instead of per-row vocab logits."""
        mc = self.model_config
        from production_stack_tpu.engine.sampler import sample_tokens

        attn = self._packed_attn_closure(s_pad, t_pad)

        def step(params, kc, vc, tokens, positions, write_slots, tables,
                 q_starts, total_lens, temps, top_ps, top_ks, min_ps,
                 keys, lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            attn_fn = functools.partial(
                attn,
                tables=tables,
                q_starts=q_starts,
                positions2d=positions.reshape(s_pad, t_pad),
                total_lens=total_lens,
            )
            logits, kc, vc = self._forward(
                mc, params, tokens, positions, kc, vc, write_slots,
                lambda q, l, k, v: attn_fn(q, l, k, v),
                logits_rows=jnp.arange(s_pad * t_pad),
                lora=lora, lora_slots=lora_slots,
            )
            sampled = sample_tokens(logits, temps, top_ps, top_ks, keys,
                                    min_p=min_ps)
            return sampled, kc, vc

        return jax.jit(step, donate_argnums=(1, 2),
                       **self._step_jit_kwargs(1))

    def verify_batch(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
        row_sampling: tuple,
        lora_slots: list[int] | None = None,
    ) -> np.ndarray:
        """Run one packed verification forward over n lanes' draft
        chunks; returns (n, t_pad) int32 — row (s, j) is the token the
        seeded sampler picks from lane s's distribution after consuming
        chunk row j. `row_sampling` = per-lane (temps, top_ps, top_ks,
        seeds, key_starts) arrays; row j of lane s samples with key
        (seeds[s], key_starts[s] + j), the key autoregressive step j
        would use. KV for every fed row is written; rejected rows'
        garbage KV sits beyond every reader's context length until real
        tokens overwrite it."""
        n = len(chunks)
        (s_pad, t_pad, c_pad, tokens, positions_dev, write_slots,
         q_starts, tl_full, tables) = self._packed_host_prep(
            chunks, start_positions, block_tables, total_lens
        )

        # per-ROW sampling arrays, padded lane-major to (s_pad * t_pad,)
        (l_temps, l_top_ps, l_top_ks, l_min_ps, l_seeds,
         l_starts) = row_sampling
        temps = np.zeros((s_pad, t_pad), np.float32)
        top_ps = np.ones((s_pad, t_pad), np.float32)
        top_ks = np.full((s_pad, t_pad), -1, np.int32)
        min_ps_g = np.zeros((s_pad, t_pad), np.float32)
        keys = np.zeros((s_pad, t_pad, 2), np.uint32)
        temps[:n] = np.asarray(l_temps, np.float32)[:, None]
        top_ps[:n] = np.asarray(l_top_ps, np.float32)[:, None]
        top_ks[:n] = np.asarray(l_top_ks, np.int32)[:, None]
        min_ps_g[:n] = np.asarray(l_min_ps, np.float32)[:, None]
        keys[:n, :, 0] = np.asarray(l_seeds, np.uint32)[:, None]
        keys[:n, :, 1] = (
            np.asarray(l_starts, np.int64)[:, None]
            + np.arange(t_pad, dtype=np.int64)[None, :]
        ).astype(np.uint32)

        key = (s_pad, t_pad, c_pad)
        if key not in self._verify_batch_fns:
            logger.info(
                "compiling batched verify step s=%d t=%d ctx=%d",
                s_pad, t_pad, c_pad,
            )
            self._note_compile("verify")
            self._verify_batch_fns[key] = self._build_verify_batch(
                s_pad, t_pad, c_pad
            )
        fn = self._verify_batch_fns[key]
        lora_kw = self._packed_lora_kwargs(lora_slots, n, s_pad, t_pad)
        sampled, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens.reshape(-1)),
            jnp.asarray(positions_dev.reshape(-1)),
            jnp.asarray(write_slots.reshape(-1)),
            jnp.asarray(tables),
            jnp.asarray(q_starts),
            jnp.asarray(tl_full),
            jnp.asarray(temps.reshape(-1)),
            jnp.asarray(top_ps.reshape(-1)),
            jnp.asarray(top_ks.reshape(-1)),
            jnp.asarray(min_ps_g.reshape(-1)),
            jnp.asarray(keys.reshape(-1, 2)),
            **lora_kw,
        )
        return np.asarray(sampled).reshape(s_pad, t_pad)[:n]

    def _packed_host_prep(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
    ):
        """Host-side packing shared by prefill_batch and verify_batch:
        bucket n ragged chunks to (s_pad, t_pad), build per-row
        positions/write-slots (padded rows park at position 0 writing
        the trash slot) and per-lane attention tables for the active
        impl. Returns (s_pad, t_pad, c_pad, tokens, positions_dev,
        write_slots, q_starts, tl_full, tables)."""
        n = len(chunks)
        s_pad = next_pow2(max(n, 1))
        t_pad = self._prefill_bucket(max(len(c) for c in chunks))
        c_pad = max(self._ctx_bucket(tl) for tl in total_lens)

        tokens = np.zeros((s_pad, t_pad), dtype=np.int32)
        positions = np.full((s_pad, t_pad), -1, dtype=np.int32)
        write_slots = np.zeros((s_pad, t_pad), dtype=np.int32)
        q_starts = np.zeros((s_pad,), dtype=np.int32)
        tl_full = np.ones((s_pad,), dtype=np.int32)
        for s, (ids, start) in enumerate(zip(chunks, start_positions)):
            t = len(ids)
            tokens[s, :t] = ids
            positions[s, :t] = np.arange(start, start + t)
            write_slots[s] = self._slots_for_positions(
                block_tables[s], positions[s]
            )
            q_starts[s] = start
            tl_full[s] = total_lens[s]
        # padded rows/sequences: position -1 -> rope of position 0, write
        # to the trash slot; their attention output is never read
        positions_dev = np.where(positions < 0, 0, positions).astype(
            np.int32
        )
        if self.attention_impl == "pallas":
            n_pages = c_pad // self.block_size
            tables = np.stack([
                self._padded_block_table(
                    block_tables[s] if s < n else [], n_pages
                )
                for s in range(s_pad)
            ])
        else:
            tables = np.zeros((s_pad, c_pad), dtype=np.int32)
            for s in range(n):
                tables[s] = self._gather_slots_for_table(
                    block_tables[s], c_pad
                )
        return (s_pad, t_pad, c_pad, tokens, positions_dev, write_slots,
                q_starts, tl_full, tables)

    def _packed_lora_kwargs(
        self, lora_slots, n: int, s_pad: int, t_pad: int
    ) -> dict:
        """Uniform-adapter fast path vs per-token slot vector, shared by
        the packed prefill/verify entries."""
        if self.lora_manager is None:
            return {}
        slots = lora_slots if lora_slots is not None else [0] * n
        if len(set(slots)) <= 1:
            # whole group shares one adapter: uniform fast path
            slots_arg = jnp.int32(slots[0] if slots else 0)
        else:
            per_tok = np.zeros((s_pad, t_pad), dtype=np.int32)
            for s, slot in enumerate(slots):
                per_tok[s] = slot
            slots_arg = jnp.asarray(per_tok.reshape(-1))
        return {
            "lora": self.lora_manager.buffers,
            "lora_slots": slots_arg,
        }

    def _packed_attn_closure(self, s_pad: int, t_pad: int):
        """Attention over s_pad back-to-back chunks on one flat token
        axis (row s*t_pad + r is row r of chunk s) — shared by the
        packed-prefill and batched-verify builders."""
        mc = self.model_config
        scale = self._scale

        if self.attention_impl == "pallas" and self.ragged_kernel:
            # ONE ragged-kernel launch over the whole packed token
            # axis: every block of t_pad (pow2 >= RAGGED_TQ) belongs
            # to exactly one lane, so per-block segment metadata is a
            # static lane map + the traced q_starts — the s_pad
            # unrolled per-lane kernel ladder collapses to one grid
            tq = RAGGED_TQ
            n_blk = (s_pad * t_pad) // tq
            lane_of = np.arange(n_blk, dtype=np.int32) * tq // t_pad
            off_in = (np.arange(n_blk, dtype=np.int32) * tq) % t_pad

            def attn(q, l, kc, vc, tables, q_starts, positions2d,
                     total_lens):
                blk_seg = jnp.arange(n_blk + 1, dtype=jnp.int32)
                seg_meta = jnp.stack([
                    jnp.asarray(lane_of),
                    jnp.zeros((n_blk,), jnp.int32),
                    jnp.full((n_blk,), tq, jnp.int32),
                    q_starts[lane_of] + jnp.asarray(off_in),
                ], axis=1)
                return self._attn(
                    "ragged", q, l, kc, vc, tables, blk_seg, seg_meta
                )
        elif self.attention_impl == "pallas":

            # tables: (s_pad, P) per-sequence padded block tables;
            # q_starts: (s_pad,) absolute position of each chunk's row 0
            def attn(q, l, kc, vc, tables, q_starts, positions2d,
                     total_lens):
                qs = q.reshape(s_pad, t_pad, mc.num_heads, mc.head_dim)
                outs = []
                for s in range(s_pad):
                    outs.append(self._attn(
                        "prefill", qs[s], l, kc, vc, tables[s],
                        q_starts[s],
                    ))
                return jnp.concatenate(outs, axis=0)
        else:

            # tables: (s_pad, c_pad) per-sequence gather slots
            def attn(q, l, kc, vc, tables, q_starts, positions2d,
                     total_lens):
                # advanced-index hoisting (see prefill): (s, c, nkv, d)
                k_ctx = kc[l, :, tables]
                v_ctx = vc[l, :, tables]
                qs = q.reshape(s_pad, t_pad, mc.num_heads, mc.head_dim)
                out = jax.vmap(
                    functools.partial(
                        xla_attn.context_attention_prefill,
                        window=self.model_config.sliding_window,
                    ),
                    in_axes=(0, 0, 0, 0, 0, None),
                )(qs, k_ctx, v_ctx, positions2d, total_lens, scale)
                return out.reshape(
                    s_pad * t_pad, mc.num_heads, mc.head_dim
                )

        return attn

    def _make_prefill_batch_step(self, s_pad: int, t_pad: int):
        """The raw (un-jitted) packed cross-sequence prefill step: chunks
        from s_pad sequences run in ONE device program (one dispatch
        instead of s_pad — burst-TTFT fix; reference capability bar is
        vLLM's batched chunked prefill, reference:
        helm/templates/deployment-vllm-multi.yaml:140-146).

        The flat token axis carries the s_pad chunks back to back
        (row s*t_pad + r is row r of chunk s): the embedding, projections,
        MLP, and cache scatters are already per-token, so they batch for
        free on the MXU; only attention needs per-sequence handling. The
        Pallas path unrolls the hardware-validated single-sequence kernel
        s_pad times inside the jitted step — TPU grid programs run
        sequentially on the core anyway, so this matches a batched-grid
        kernel's schedule without forking a second Mosaic kernel.

        Shared by _build_prefill_batch (which jits it) and the ragged
        dispatch builder (which composes it with the decode scan inside
        ONE jitted round)."""
        mc = self.model_config
        from production_stack_tpu.engine.sampler import sample_tokens

        attn = self._packed_attn_closure(s_pad, t_pad)

        def step(params, kc, vc, tokens, positions, write_slots, tables,
                 q_starts, total_lens, last_rows, temps, top_ps, top_ks,
                 min_ps, keys, lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            attn_fn = functools.partial(
                attn,
                tables=tables,
                q_starts=q_starts,
                positions2d=positions.reshape(s_pad, t_pad),
                total_lens=total_lens,
            )
            logits, kc, vc = self._forward(
                mc, params, tokens, positions, kc, vc, write_slots,
                lambda q, l, k, v: attn_fn(q, l, k, v),
                logits_rows=last_rows,
                lora=lora, lora_slots=lora_slots,
            )
            # on-device first-token sampling (see _build_prefill): the
            # host fetches (s_pad,) int32, not (s_pad, vocab) f32
            sampled = sample_tokens(logits, temps, top_ps, top_ks, keys,
                                    min_p=min_ps)
            return sampled, logits, kc, vc

        return step

    def _make_prefill_batch_packed(self, s_pad: int, t_pad: int,
                                   c_pad: int):
        """Fused-buffer wrapper of _make_prefill_batch_step: one i32
        operand (layout _packed_prefill_pack_layout), unpacked on device
        (see _build_prefill). Un-jitted — _build_prefill_batch jits it,
        the ragged builder inlines it."""
        step = self._make_prefill_batch_step(s_pad, t_pad)
        layout, _size = self._packed_prefill_pack_layout(
            s_pad, t_pad, c_pad
        )

        def _seg(packed, name, _lo=layout):
            return self._pack_seg(packed, _lo, name)

        def packed_step(params, kc, vc, packed, lora=None,
                        lora_slots=None):
            def f32(name):
                return jax.lax.bitcast_convert_type(
                    _seg(packed, name), jnp.float32
                )

            return step(
                params, kc, vc,
                _seg(packed, "tokens"),
                _seg(packed, "positions"),
                _seg(packed, "write_slots"),
                _seg(packed, "tables"),
                _seg(packed, "q_starts"),
                _seg(packed, "total_lens"),
                _seg(packed, "last_rows"),
                f32("temps"), f32("top_ps"),
                _seg(packed, "top_ks"), f32("min_ps"),
                jax.lax.bitcast_convert_type(
                    _seg(packed, "keys"), jnp.uint32
                ),
                lora=lora, lora_slots=lora_slots,
            )

        return packed_step

    def _build_prefill_batch(self, s_pad: int, t_pad: int, c_pad: int):
        """Jitted packed cross-sequence prefill (raw-args variant, or
        the fused-buffer variant under the prefill pipeline)."""
        jit_kw = self._step_jit_kwargs(2)
        if not self.prefill_pipeline:
            return jax.jit(
                self._make_prefill_batch_step(s_pad, t_pad),
                donate_argnums=(1, 2), **jit_kw,
            )
        return jax.jit(
            self._make_prefill_batch_packed(s_pad, t_pad, c_pad),
            donate_argnums=(1, 2), **jit_kw,
        )

    def _decode_attn_closure(self):
        """The decode-shaped attention callback shared by the
        single-step, fused-K, and ragged-round builders: the unified
        ragged kernel in all-decode-row configuration (decode lanes
        are single-row segments of the one grid — the SAME program the
        mixed rounds launch), the composed per-sequence-grid decode
        kernel (--no-ragged-kernel A/B control), or the XLA gather
        path. `tables` = padded per-sequence block tables (b, pages)
        on the pallas paths, per-position gather slots (b, c_pad) on
        the XLA path."""
        scale = self._scale
        if self.attention_impl == "pallas" and self.ragged_kernel:
            tq = RAGGED_TQ

            def attn(q, l, kc, vc, tables, context_lens):
                b = q.shape[0]
                r_pad = _ceil_tq(b)
                n_blk = r_pad // tq
                qp = jnp.pad(q, ((0, r_pad - b), (0, 0), (0, 0)))
                # one single-row segment per lane; blocks hold up to
                # TQ lanes (CSR offsets clip at the live lane count)
                blk_seg = jnp.minimum(
                    jnp.arange(n_blk + 1, dtype=jnp.int32) * tq, b
                )
                lanes = jnp.arange(b, dtype=jnp.int32)
                seg_meta = jnp.stack([
                    lanes,
                    lanes % tq,
                    jnp.ones((b,), jnp.int32),
                    context_lens - 1,
                ], axis=1)
                out = self._attn(
                    "ragged", qp, l, kc, vc, tables, blk_seg, seg_meta
                )
                return out[:b]
        elif self.attention_impl == "pallas":

            def attn(q, l, kc, vc, tables, context_lens):
                # q: (b, nq, d); kc/vc: full (L, nkv, slots, d) — the
                # kernel DMAs pages straight from HBM, no gathered
                # copy. Under TP the kernel is shard_mapped: each chip
                # runs it on its local kv-head shard (GQA groups are
                # chip-local)
                return self._attn(
                    "decode", q, l, kc, vc, tables, context_lens
                )
        else:

            def attn(q, l, kc, vc, tables, context_lens):
                # advanced-index hoisting (see prefill): (b, c, nkv, d)
                k_ctx = kc[l, :, tables]
                v_ctx = vc[l, :, tables]
                return xla_attn.context_attention_decode(
                    q, k_ctx, v_ctx, context_lens, scale,
                    window=self.model_config.sliding_window,
                )

        return attn

    def _build_decode(self, b: int, c_pad: int):
        mc = self.model_config
        attn = self._decode_attn_closure()

        def step(params, kc, vc, tokens, positions, write_slots,
                 tables, context_lens, lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            attn_fn = functools.partial(
                attn, tables=tables, context_lens=context_lens
            )
            logits, kc, vc = self._forward(
                mc, params, tokens, positions, kc, vc, write_slots,
                lambda q, l, k, v: attn_fn(q, l, k, v),
                logits_rows=jnp.arange(b),
                lora=lora, lora_slots=lora_slots,
            )
            return logits, kc, vc

        return jax.jit(step, donate_argnums=(1, 2), **self._step_jit_kwargs())

    def _decode_pack_layout(self, b: int, c_pad: int, chained: bool,
                            guided: bool = False,
                            stop_cap: int | None = None):
        """Static layout of the ONE int32 host->device buffer a
        multi-step decode dispatch ships.

        Through a remote/tunneled chip every separate buffer creation
        pays link latency; packing the ~8 small per-dispatch arrays
        (tokens, positions, context lens, sampling params, page tables)
        into one transfer makes the h2d cost one RPC instead of eight.
        f32/u32 fields travel bitcast as i32 and are bitcast back on
        device. Returns ({name: (offset, shape)}, total_len).

        `stop_cap` (device-side stop masks): None = the fixed-trip
        program without stop fields (--no-device-stop control); an int
        adds the per-lane EOS id, min_tokens gate, remaining-budget
        countdown, and — when > 0 — a (b, stop_cap) padded
        stop-token-id matrix."""
        n_pages = c_pad // self.block_size
        fields: list[tuple[str, tuple[int, ...]]] = []
        if not chained:
            fields.append(("tokens", (b,)))
        fields += [
            ("positions", (b,)),
            ("ctx", (b,)),
            ("temps", (b,)),
            ("top_ps", (b,)),
            ("top_ks", (b,)),
            ("min_ps", (b,)),
            ("keys", (b, 2)),
            ("page_tables", (b, n_pages)),
        ]
        if guided:
            # per-lane DFA state + machine row (the big tables travel
            # separately, device-cached across dispatches)
            fields += [("g_state", (b,)), ("g_lane", (b,))]
        if stop_cap is not None:
            fields += [
                ("stop_eos", (b,)),
                ("stop_min", (b,)),
                ("stop_budget", (b,)),
            ]
            if stop_cap > 0:
                fields.append(("stop_ids", (b, stop_cap)))
        if self.attention_impl != "pallas":
            fields.append(("gather_tables", (b, c_pad)))
        return self._layout_of(fields)

    def _make_decode_multi_step(self, b: int, c_pad: int, k_steps: int,
                                use_penalties: bool = False,
                                want_logprobs: bool = False,
                                chained: bool = False,
                                guided_shapes: tuple | None = None,
                                bias_cap: int = 0,
                                stop_cap: int | None = None):
        """K fused decode+sample iterations per dispatch (the raw,
        un-jitted step — _build_decode_multi jits it; the ragged
        dispatch builder composes it with the packed prefill step
        inside ONE jitted round).

        The serving loop's per-step cost is dominated by the
        device-to-host fetch of the sampled token (one tunnel/PCIe RTT —
        measured 143 ms through the axon relay, ~100x the 3B decode
        compute). Sampling on device and chaining K iterations inside
        one jitted scan amortises that RTT over K tokens (vLLM's
        --num-scheduler-steps semantics; MaxText's on-device sampling
        loop is the same idea). The per-iteration sampling keys are
        (seed, generated_len + i) — bit-identical to K single steps, so
        multi-step changes throughput, never outputs.

        Host-side inputs arrive as ONE packed i32 buffer
        (`_decode_pack_layout`); `chained=True` builds the variant whose
        tokens come from the previous round's on-device output instead.

        `stop_cap` is not None => device-side stop masks (elastic
        fused decode): a per-lane done mask rides the loop carry. A
        lane is done once its per-round append count reaches its
        remaining budget (max_tokens/max_model_len countdown) or it
        samples its EOS / one of its stop_token_ids at or past its
        min_tokens gate. A done lane FREEZES — its sampled slot is
        pinned to STOP_PAD_TOKEN, its KV-slot write is redirected to
        the trash slot, its position/context stop advancing, and its
        penalty-count/guided-DFA state stops updating — so overshoot
        slots cost no cache or state corruption and the loop runs as a
        lax.while_loop that exits the whole round as soon as EVERY
        lane is done. The program then additionally returns a (b,)
        int32 per-lane VALID count (tokens sampled before freezing);
        tokens at positions >= valid[lane] are pad, never host-applied.
        Tokens below the valid count are bit-identical to the
        fixed-trip program — masking engages strictly after the stop
        token is sampled."""
        core = self._decode_round_core(
            b, c_pad, k_steps, use_penalties=use_penalties,
            want_logprobs=want_logprobs, chained=chained,
            guided_shapes=guided_shapes, bias_cap=bias_cap,
            stop_cap=stop_cap,
        )

        def step(params, kc, vc, packed, chained_tokens=None,
                 g_token_class=None, g_class_mask=None, g_class_trans=None,
                 gen_ids=None, presence=None, frequency=None,
                 repetition=None, lb_ids=None, lb_vals=None,
                 lora=None, lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            consts, carry0 = core["unpack"](
                packed, chained_tokens=chained_tokens,
                g_token_class=g_token_class, g_class_mask=g_class_mask,
                g_class_trans=g_class_trans, gen_ids=gen_ids,
                presence=presence, frequency=frequency,
                repetition=repetition, lb_ids=lb_ids, lb_vals=lb_vals,
            )
            return core["run"](params, kc, vc, consts, carry0,
                               lora=lora, lora_slots=lora_slots)

        return step

    def _decode_round_core(self, b: int, c_pad: int, k_steps: int,
                           use_penalties: bool = False,
                           want_logprobs: bool = False,
                           chained: bool = False,
                           guided_shapes: tuple | None = None,
                           bias_cap: int = 0,
                           stop_cap: int | None = None):
        """Shared internals of the fused-K decode round, factored into
        unpack / forward / post-sample / loop closures so the packed
        dispatch (_make_decode_multi_step) and the fused lane-typed
        round (_build_ragged_rows — whose FIRST decode iteration's
        forward is welded to the prefill rows inside one ragged-kernel
        grid) run IDENTICAL per-step math. `run(first_logits=...)`
        consumes an externally computed step-0 logits and continues
        the loop from iteration 1; without it the loop is exactly the
        packed dispatch's scan/while_loop."""
        mc = self.model_config
        bs = self.block_size
        from production_stack_tpu.engine.sampler import (
            LOGPROB_CAP,
            STOP_PAD_TOKEN,
            apply_penalties,
            sample_tokens,
            stop_hit,
            token_logprobs,
        )

        attn = self._decode_attn_closure()
        use_pages = self.attention_impl == "pallas"
        use_stop = stop_cap is not None
        layout, _total = self._decode_pack_layout(
            b, c_pad, chained, guided=guided_shapes is not None,
            stop_cap=stop_cap,
        )

        def _seg(packed, name, _lo=layout):
            return self._pack_seg(packed, _lo, name)

        lane = jnp.arange(b)

        def unpack(packed, chained_tokens=None, g_token_class=None,
                   g_class_mask=None, g_class_trans=None, gen_ids=None,
                   presence=None, frequency=None, repetition=None,
                   lb_ids=None, lb_vals=None):
            """Decode-pack fields -> (consts dict, initial carry)."""
            tokens = (
                chained_tokens if chained else _seg(packed, "tokens")
            )
            positions = _seg(packed, "positions")
            context_lens = _seg(packed, "ctx")
            page_tables = _seg(packed, "page_tables")
            consts = {
                "temps": jax.lax.bitcast_convert_type(
                    _seg(packed, "temps"), jnp.float32
                ),
                "top_ps": jax.lax.bitcast_convert_type(
                    _seg(packed, "top_ps"), jnp.float32
                ),
                "top_ks": _seg(packed, "top_ks"),
                "min_ps": jax.lax.bitcast_convert_type(
                    _seg(packed, "min_ps"), jnp.float32
                ),
                "base_keys": jax.lax.bitcast_convert_type(
                    _seg(packed, "keys"), jnp.uint32
                ),
                "page_tables": page_tables,
                "attn_tables": (
                    page_tables if use_pages
                    else _seg(packed, "gather_tables")
                ),
                "presence": presence,
                "frequency": frequency,
                "repetition": repetition,
                "lb_ids": lb_ids,
                "lb_vals": lb_vals,
                "g_class_mask": g_class_mask,
                "g_class_trans": g_class_trans,
            }

            if use_penalties:
                # per-lane generated-token counts, maintained ON DEVICE
                # across the scan so penalty sampling needs no host round
                # trip (gen_ids: (b, c_pad) int32, -1 padded)
                valid = (gen_ids >= 0).astype(jnp.float32)
                counts0 = jnp.zeros(
                    (b, mc.vocab_size), jnp.float32
                ).at[lane[:, None], jnp.maximum(gen_ids, 0)].add(valid)
            else:
                counts0 = jnp.zeros((b, 1), jnp.float32)  # unused carry

            if guided_shapes is not None:
                # (b, V) class of every token for each lane's machine,
                # gathered once per dispatch outside the scan
                consts["lane_tc"] = g_token_class[_seg(packed, "g_lane")]
                g_state0 = _seg(packed, "g_state")
            else:
                consts["lane_tc"] = None
                g_state0 = jnp.zeros((b,), jnp.int32)  # unused carry

            if use_stop:
                consts["eos_ids"] = _seg(packed, "stop_eos")
                consts["min_need"] = _seg(packed, "stop_min")
                budget = _seg(packed, "stop_budget")
                consts["budget"] = budget
                consts["s_ids"] = (
                    _seg(packed, "stop_ids") if stop_cap else None
                )
                # padded lanes ship budget 0: done from iteration 0, so
                # an all-real-lanes-finished round early-exits even
                # when the static lane count exceeds the live batch
                done0 = budget <= 0
            else:
                consts["s_ids"] = None
                done0 = jnp.zeros((b,), bool)  # unused carry
            valid0 = jnp.zeros((b,), jnp.int32)
            carry0 = (tokens, positions, context_lens, counts0,
                      g_state0, done0, valid0)
            return consts, carry0

        def fwd_args(carry, consts):
            """(tokens, positions, write_slots, ctx) for one decode
            forward — shared by the in-loop forward and the fused
            round's step-0 mixed forward."""
            tokens, positions, ctx = carry[0], carry[1], carry[2]
            done = carry[5]
            # slot for each lane's current position from its block
            # table (idle lanes carry the zero table -> trash block 0;
            # K <= block_size keeps them inside it)
            write_slots = (
                consts["page_tables"][lane, positions // bs] * bs
                + positions % bs
            )
            if use_stop:
                # frozen lanes write the trash slot: a done lane's
                # overshoot KV must never land past its real end
                write_slots = jnp.where(done, 0, write_slots)
            return tokens, positions, write_slots, ctx

        def fwd(params, kc, vc, carry, consts, lora, lora_slots):
            tokens, positions, write_slots, ctx = fwd_args(carry, consts)
            attn_fn = functools.partial(
                attn, tables=consts["attn_tables"], context_lens=ctx,
            )
            logits, kc, vc = self._forward(
                mc, params, tokens, positions, kc, vc, write_slots,
                lambda q, l, k, v: attn_fn(q, l, k, v),
                logits_rows=lane,
                lora=lora, lora_slots=lora_slots,
            )
            return logits, kc, vc

        def post(logits, carry, i, consts):
            """Sample + stop/penalty/guided state advance for one
            iteration's logits; returns (carry', ys_i)."""
            (tokens, positions, ctx, counts, g_state, done,
             valid) = carry
            if use_penalties:
                logits = apply_penalties(
                    logits, counts > 0, counts, consts["presence"],
                    consts["frequency"], consts["repetition"],
                )
            if bias_cap:
                # OpenAI logit_bias: per-lane sparse additive bias
                # (padding adds 0.0 to token 0 — a no-op), applied
                # after penalties and before any guided mask, same
                # order as the host path (_sample)
                logits = logits.at[
                    lane[:, None], consts["lb_ids"]
                ].add(consts["lb_vals"])
            if guided_shapes is not None:
                # constraint mask from the lane's DFA state (same
                # penalties->mask->sample order as the host path)
                mask_c = consts["g_class_mask"][g_state]  # (b, C)
                allowed = jnp.take_along_axis(
                    mask_c, consts["lane_tc"], axis=1
                )                                         # (b, V)
                logits = jnp.where(allowed, logits, -jnp.inf)
            keys = consts["base_keys"].at[:, 1].add(
                jnp.asarray(i).astype(jnp.uint32)
            )
            nxt = sample_tokens(logits, consts["temps"],
                                consts["top_ps"], consts["top_ks"],
                                keys, min_p=consts["min_ps"])
            live = jnp.logical_not(done)
            if use_stop:
                # pin frozen lanes' sampled slots to the pad token
                # (the host reads only valid[lane] tokens anyway)
                nxt = jnp.where(done, STOP_PAD_TOKEN, nxt)
            if guided_shapes is not None:
                cls = jnp.take_along_axis(
                    consts["lane_tc"], nxt[:, None], axis=1
                )[:, 0]
                new_g = consts["g_class_trans"][g_state, cls]
                # a frozen lane's DFA state stops stepping (the pad
                # token is not part of its stream)
                g_state = (
                    jnp.where(done, g_state, new_g)
                    if use_stop else new_g
                )
            if use_penalties:
                # frozen lanes stop updating penalty counts: pinned
                # pad tokens are not generated output
                counts = counts.at[lane, nxt].add(
                    live.astype(jnp.float32) if use_stop else 1.0
                )
            valid = valid + live.astype(jnp.int32)
            if use_stop:
                # the sampled token is valid (the stop token itself
                # is appended, same as the host path); the lane
                # freezes FROM THE NEXT iteration. Budget first,
                # then the min_tokens-gated EOS/stop-id check —
                # check_stop's exact ordering.
                hit = stop_hit(nxt, consts["eos_ids"], consts["s_ids"])
                done = done | (valid >= consts["budget"]) | (
                    live & hit & (valid >= consts["min_need"])
                )
                adv = jnp.where(done, 0, 1)
            else:
                adv = 1
            if want_logprobs:
                # on-device logprobs ride the same single fetch —
                # (k, b) chosen + (k, b, CAP) top alternatives
                ys = (nxt, *token_logprobs(logits, nxt))
            else:
                ys = nxt
            carry = (nxt, positions + adv, ctx + adv, counts,
                     g_state, done, valid)
            return carry, ys

        def run(params, kc, vc, consts, carry0, lora=None,
                lora_slots=None, first_logits=None):
            def one(kc, vc, carry, i):
                logits, kc, vc = fwd(params, kc, vc, carry, consts,
                                     lora, lora_slots)
                carry, ys = post(logits, carry, i, consts)
                return kc, vc, carry, ys

            if not use_stop:

                def scan_one(sc, i):
                    kc, vc, c = sc
                    kc, vc, c, ys = one(kc, vc, c, i)
                    return (kc, vc, c), ys

                if first_logits is None:
                    (kc, vc, _), ys = jax.lax.scan(
                        scan_one, (kc, vc, carry0), jnp.arange(k_steps)
                    )
                    return ys, kc, vc  # ys: (k, b) toks [+ lp arrays]
                # fused lane-typed round: step 0's forward already ran
                # (welded to the prefill rows); apply its post half
                # here and scan the remaining iterations
                c, ys0 = post(first_logits, carry0, jnp.int32(0),
                              consts)
                (kc, vc, _), ys_rest = jax.lax.scan(
                    scan_one, (kc, vc, c), jnp.arange(1, k_steps)
                )
                ys = jax.tree_util.tree_map(
                    lambda a, r: jnp.concatenate([a[None], r], axis=0),
                    ys0, ys_rest,
                )
                return ys, kc, vc

            # device-stop variant: while_loop over preallocated output
            # rows so the round EXITS as soon as every lane is done —
            # an all-finished tail iteration would otherwise still pay
            # the full forward. Unwritten rows stay at the pad token;
            # the host consumes only valid[lane] tokens per lane.
            toks_buf = jnp.full((k_steps, b), STOP_PAD_TOKEN, jnp.int32)
            lp_bufs = ()
            if want_logprobs:
                lp_bufs = (
                    jnp.zeros((k_steps, b), jnp.float32),
                    jnp.zeros((k_steps, b, LOGPROB_CAP), jnp.float32),
                    jnp.zeros((k_steps, b, LOGPROB_CAP), jnp.int32),
                )

            def cond(state):
                i, c = state[0], state[3]
                done = c[5]
                return jnp.logical_and(
                    i < k_steps, jnp.logical_not(jnp.all(done))
                )

            def body(state):
                i, kc, vc, c, tb = state[:5]
                lps = list(state[5:])
                kc, vc, c, ys = one(kc, vc, c, i)
                if want_logprobs:
                    nxt, ch, tv, ti = ys
                    lps = [
                        lps[0].at[i].set(ch),
                        lps[1].at[i].set(tv),
                        lps[2].at[i].set(ti),
                    ]
                else:
                    nxt = ys
                tb = tb.at[i].set(nxt)
                return (i + 1, kc, vc, c, tb, *lps)

            c0 = carry0
            i0 = jnp.int32(0)
            if first_logits is not None:
                # fused round: seed the buffers with step 0's post
                # half, then loop from iteration 1 (the while cond
                # still early-exits once every lane is done)
                c0, ys0 = post(first_logits, carry0, jnp.int32(0),
                               consts)
                if want_logprobs:
                    nxt0, ch0, tv0, ti0 = ys0
                    lp_bufs = (
                        lp_bufs[0].at[0].set(ch0),
                        lp_bufs[1].at[0].set(tv0),
                        lp_bufs[2].at[0].set(ti0),
                    )
                else:
                    nxt0 = ys0
                toks_buf = toks_buf.at[0].set(nxt0)
                i0 = jnp.int32(1)
            state = jax.lax.while_loop(
                cond, body,
                (i0, kc, vc, c0, toks_buf, *lp_bufs),
            )
            _, kc, vc, c, tb = state[:5]
            valid = c[6]
            if want_logprobs:
                ys = (tb, *state[5:8], valid)
            else:
                ys = (tb, valid)
            return ys, kc, vc  # ys: (toks, [lp arrays,] valid)

        return {
            "layout": layout,
            "unpack": unpack,
            "fwd_args": fwd_args,
            "run": run,
            "lane": lane,
        }

    def _build_decode_multi(self, b: int, c_pad: int, k_steps: int,
                            use_penalties: bool = False,
                            want_logprobs: bool = False,
                            chained: bool = False,
                            guided_shapes: tuple | None = None,
                            bias_cap: int = 0,
                            stop_cap: int | None = None):
        """Jitted fused-K decode program (see _make_decode_multi_step)."""
        return jax.jit(
            self._make_decode_multi_step(
                b, c_pad, k_steps, use_penalties=use_penalties,
                want_logprobs=want_logprobs, chained=chained,
                guided_shapes=guided_shapes, bias_cap=bias_cap,
                stop_cap=stop_cap,
            ),
            donate_argnums=(1, 2), **self._step_jit_kwargs(),
        )

    # -- host-side helpers -------------------------------------------------
    # stackcheck: not-hot — host-side batch staging: numpy over python
    # block tables, no device arrays involved
    def _slots_for_positions(
        self, block_table: list[int], positions: np.ndarray
    ) -> np.ndarray:
        """Cache slots for absolute positions; positions beyond the table
        map to the trash slot 0."""
        bt = np.asarray(block_table, dtype=np.int32)
        max_pos = len(bt) * self.block_size
        safe = np.clip(positions, 0, max_pos - 1) if len(bt) else positions * 0
        slots = (
            bt[safe // self.block_size] * self.block_size
            + safe % self.block_size
        ).astype(np.int32)
        slots[positions >= max_pos] = 0
        slots[positions < 0] = 0
        return slots

    # stackcheck: not-hot — host-side batch staging: numpy over python
    # block tables, no device arrays involved
    def _padded_block_table(
        self, block_table: list[int], n_pages: int
    ) -> np.ndarray:
        """Block table padded/truncated to n_pages; padding pages point at
        the null block 0 (shared convention of both attention impls)."""
        bt = np.zeros((n_pages,), dtype=np.int32)
        use = min(len(block_table), n_pages)
        if use:
            bt[:use] = np.asarray(block_table[:use], dtype=np.int32)
        return bt

    def _gather_slots_for_table(
        self, block_table: list[int], c_pad: int
    ) -> np.ndarray:
        bt = self._padded_block_table(
            block_table, c_pad // self.block_size
        )
        offs = np.arange(self.block_size, dtype=np.int32)
        return (bt[:, None] * self.block_size + offs).reshape(-1)

    # -- public API --------------------------------------------------------
    @staticmethod
    # stackcheck: not-hot — host-side dispatch staging: np.asarray over
    # python sampling-param lists, no device arrays involved
    def _sampling_args(
        n: int, sampling=None
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
               np.ndarray]:
        """Pad per-sequence sampling params to n rows (greedy defaults)."""
        temps = np.zeros((n,), np.float32)
        top_ps = np.ones((n,), np.float32)
        top_ks = np.full((n,), -1, np.int32)
        min_ps = np.zeros((n,), np.float32)
        keys = np.zeros((n, 2), np.uint32)
        if sampling is not None:
            t, p, k, mp, kd = sampling
            m = len(np.asarray(t).reshape(-1))
            temps[:m] = np.asarray(t, np.float32).reshape(-1)
            top_ps[:m] = np.asarray(p, np.float32).reshape(-1)
            top_ks[:m] = np.asarray(k, np.int32).reshape(-1)
            min_ps[:m] = np.asarray(mp, np.float32).reshape(-1)
            keys[:m] = np.asarray(kd, np.uint32).reshape(m, 2)
        return temps, top_ps, top_ks, min_ps, keys

    def prefill(
        self,
        token_ids: list[int],
        start_pos: int,
        block_table: list[int],
        total_len: int,
        lora_slot: int = 0,
        sampling=None,
        prompt_lp_targets: list[int] | None = None,
        staged: tuple | None = None,
    ) -> tuple:
        """Run one prefill chunk; returns (token, logits) ON DEVICE where
        `token` is the first generated token sampled from the chunk's last
        *actual* row with `sampling` = (temps, top_ps, top_ks, keys)
        (greedy/zero-key defaults), and `logits` is that row's fp32
        (vocab,) for penalty/debug paths. K/V for the chunk is written
        into the cache.

        `prompt_lp_targets` (vLLM prompt_logprobs role): per-row NEXT
        prompt token ids (-1 = no target); selects a program variant
        that additionally returns (chosen (t_pad,) f32, top_vals
        (t_pad, CAP) f32, top_ids (t_pad, CAP) i32) device arrays —
        row i scores targets[i] under the model's distribution.

        `staged` = a stage_prefill handle whose packed buffer was
        uploaded ahead of time (chunk pipelining); used only when its
        bucket key matches — the CALLER guarantees the staged content
        equals what these arguments would build."""
        want_plp = prompt_lp_targets is not None
        lora_kw = {}
        if self.lora_manager is not None:
            # scalar slot: prefill is one sequence, so the whole chunk
            # shares one adapter and forward() takes the uniform fast path
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.int32(lora_slot),
            }
        if self.prefill_pipeline:
            t_pad = self._prefill_bucket(len(token_ids))
            c_pad = self._ctx_bucket(total_len)
            packed_dev = None
            if (staged is not None and not want_plp
                    and staged[0] == ("single", t_pad, c_pad)):
                packed_dev = staged[1]  # upload already overlapped
            if packed_dev is None:
                t0 = time.perf_counter()
                t_pad, c_pad, packed = self._fill_prefill_pack(
                    token_ids, start_pos, block_table, total_len,
                    sampling=sampling,
                    prompt_lp_targets=prompt_lp_targets,
                )
                t1 = time.perf_counter()
                self._phase_add("prep", t1 - t0)
                packed_dev = jnp.asarray(packed)
                self._phase_add("h2d", time.perf_counter() - t1)
            key = (t_pad, c_pad, "plp") if want_plp else (t_pad, c_pad)
            if key not in self._prefill_fns:
                logger.info("compiling prefill step t=%d ctx=%d plp=%s",
                            t_pad, c_pad, want_plp)
                self._note_compile("prefill")
                self._prefill_fns[key] = self._build_prefill(
                    t_pad, c_pad, want_prompt_lp=want_plp
                )
            t2 = time.perf_counter()
            ys = self._prefill_fns[key](
                self.params, self.k_cache, self.v_cache, packed_dev,
                **lora_kw,
            )
            self._phase_add("dispatch", time.perf_counter() - t2)
            self.k_cache, self.v_cache = ys[-2], ys[-1]
            return ys[:-2]
        t = len(token_ids)
        t0 = time.perf_counter()
        (tokens, positions_dev, write_slots, gather_slots,
         t_pad, c_pad) = self._prefill_host_prep(
            token_ids, block_table, start_pos, total_len
        )
        key = (t_pad, c_pad, "plp") if want_plp else (t_pad, c_pad)
        if key not in self._prefill_fns:
            logger.info("compiling prefill step t=%d ctx=%d plp=%s",
                        t_pad, c_pad, want_plp)
            self._note_compile("prefill")
            self._prefill_fns[key] = self._build_prefill(
                t_pad, c_pad, want_prompt_lp=want_plp
            )
        fn = self._prefill_fns[key]
        temps, top_ps, top_ks, min_ps, keys = self._sampling_args(
            1, sampling
        )
        plp_kw = {}
        if want_plp:
            tg = np.full((t_pad,), -1, np.int32)
            tg[: len(prompt_lp_targets)] = prompt_lp_targets
            plp_kw = {"targets": jnp.asarray(tg)}
        t1 = time.perf_counter()
        self._phase_add("prep", t1 - t0)
        args = (
            jnp.asarray(tokens),
            jnp.asarray(positions_dev),
            jnp.asarray(write_slots),
            jnp.asarray(gather_slots),
            jnp.int32(total_len),
            jnp.int32(t - 1),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            jnp.asarray(min_ps),
            jnp.asarray(keys),
        )
        t2 = time.perf_counter()
        self._phase_add("h2d", t2 - t1)
        ys = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            *args,
            **plp_kw,
            **lora_kw,
        )
        self._phase_add("dispatch", time.perf_counter() - t2)
        self.k_cache, self.v_cache = ys[-2], ys[-1]
        return ys[:-2]

    def prefill_batch(
        self,
        chunks: list[list[int]],
        start_positions: list[int],
        block_tables: list[list[int]],
        total_lens: list[int],
        lora_slots: list[int] | None = None,
        sampling=None,
        staged: tuple | None = None,
    ) -> tuple[jax.Array, jax.Array]:
        """Run one prompt chunk for EACH of n sequences in a single packed
        dispatch; returns (tokens, logits) ON DEVICE — tokens (s_pad,)
        sampled from each chunk's last *actual* row with `sampling` =
        per-sequence (temps, top_ps, top_ks, keys), logits (s_pad, vocab)
        for penalty/debug paths (rows >= n are padding). K/V for every
        chunk is written into the cache.

        `staged` = a stage_prefill_batch handle (see prefill)."""
        n = len(chunks)
        if self.prefill_pipeline and self.ragged_kernel:
            # ragged-rows path: program keys on the padded ROW bucket
            # (r_pad, pc_pad), one kernel launch for any group
            r_pad, pc_pad = self._rows_dims(chunks, total_lens)
            packed_dev = None
            if (staged is not None
                    and staged[0] == ("rows", r_pad, pc_pad)):
                packed_dev = staged[1]  # upload already overlapped
            if packed_dev is None:
                t0 = time.perf_counter()
                r_pad, pc_pad, packed = self._fill_rows_prefill_pack(
                    chunks, start_positions, block_tables, total_lens,
                    sampling=sampling,
                )
                t1 = time.perf_counter()
                self._phase_add("prep", t1 - t0)
                packed_dev = jnp.asarray(packed)
                self._phase_add("h2d", time.perf_counter() - t1)
            key = ("rows", r_pad, pc_pad)
            if key not in self._prefill_batch_fns:
                logger.info(
                    "compiling ragged-rows prefill step rows=%d ctx=%d",
                    r_pad, pc_pad,
                )
                self._note_compile("prefill_rows")
                self._prefill_batch_fns[key] = self._build_prefill_rows(
                    r_pad, pc_pad
                )
            lora_kw = self._rows_lora_kwargs(lora_slots, chunks, r_pad)
            t2 = time.perf_counter()
            sampled, logits, self.k_cache, self.v_cache = (
                self._prefill_batch_fns[key](
                    self.params, self.k_cache, self.v_cache,
                    packed_dev, **lora_kw,
                )
            )
            self._phase_add("dispatch", time.perf_counter() - t2)
            return sampled, logits
        if self.prefill_pipeline:
            s_pad = next_pow2(max(n, 1))
            t_pad = self._prefill_bucket(max(len(c) for c in chunks))
            c_pad = max(self._ctx_bucket(tl) for tl in total_lens)
            packed_dev = None
            if (staged is not None
                    and staged[0] == ("packed", s_pad, t_pad, c_pad)):
                packed_dev = staged[1]  # upload already overlapped
            if packed_dev is None:
                t0 = time.perf_counter()
                s_pad, t_pad, c_pad, packed = (
                    self._fill_packed_prefill_pack(
                        chunks, start_positions, block_tables,
                        total_lens, sampling=sampling,
                    )
                )
                t1 = time.perf_counter()
                self._phase_add("prep", t1 - t0)
                packed_dev = jnp.asarray(packed)
                self._phase_add("h2d", time.perf_counter() - t1)
            key = (s_pad, t_pad, c_pad)
            if key not in self._prefill_batch_fns:
                logger.info(
                    "compiling packed prefill step s=%d t=%d ctx=%d",
                    s_pad, t_pad, c_pad,
                )
                self._note_compile("prefill_batch")
                self._prefill_batch_fns[key] = self._build_prefill_batch(
                    s_pad, t_pad, c_pad
                )
            lora_kw = self._packed_lora_kwargs(
                lora_slots, n, s_pad, t_pad
            )
            t2 = time.perf_counter()
            sampled, logits, self.k_cache, self.v_cache = (
                self._prefill_batch_fns[key](
                    self.params, self.k_cache, self.v_cache,
                    packed_dev, **lora_kw,
                )
            )
            self._phase_add("dispatch", time.perf_counter() - t2)
            return sampled, logits
        t0 = time.perf_counter()
        (s_pad, t_pad, c_pad, tokens, positions_dev, write_slots,
         q_starts, tl_full, tables) = self._packed_host_prep(
            chunks, start_positions, block_tables, total_lens
        )
        last_rows = np.zeros((s_pad,), dtype=np.int32)
        for s, ids in enumerate(chunks):
            last_rows[s] = s * t_pad + (len(ids) - 1)
        for s in range(n, s_pad):
            last_rows[s] = s * t_pad

        key = (s_pad, t_pad, c_pad)
        if key not in self._prefill_batch_fns:
            logger.info(
                "compiling packed prefill step s=%d t=%d ctx=%d",
                s_pad, t_pad, c_pad,
            )
            self._note_compile("prefill_batch")
            self._prefill_batch_fns[key] = self._build_prefill_batch(
                s_pad, t_pad, c_pad
            )
        fn = self._prefill_batch_fns[key]
        lora_kw = self._packed_lora_kwargs(lora_slots, n, s_pad, t_pad)
        temps, top_ps, top_ks, min_ps, keys = self._sampling_args(
            s_pad, sampling
        )
        t1 = time.perf_counter()
        self._phase_add("prep", t1 - t0)
        args = (
            jnp.asarray(tokens.reshape(-1)),
            jnp.asarray(positions_dev.reshape(-1)),
            jnp.asarray(write_slots.reshape(-1)),
            jnp.asarray(tables),
            jnp.asarray(q_starts),
            jnp.asarray(tl_full),
            jnp.asarray(last_rows),
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            jnp.asarray(min_ps),
            jnp.asarray(keys),
        )
        t2 = time.perf_counter()
        self._phase_add("h2d", t2 - t1)
        sampled, logits, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            *args,
            **lora_kw,
        )
        self._phase_add("dispatch", time.perf_counter() - t2)
        return sampled, logits

    def precompile_prefill(
        self,
        singles: list[tuple[int, int]] = (),
        groups: list[tuple[int, int, int]] = (),
    ) -> int:
        """Compile prefill programs ahead of serving by executing trash
        chunks whose block tables point at the TOP of the block pool.

        `singles`: (chunk_len, total_len) pairs for the single-sequence
        path; `groups`: (group_size, chunk_len, total_len) for the packed
        path. Returns the number of dispatches executed. A compile that
        lands inside a live request costs seconds (tens of seconds
        through a remote/tunneled chip) and lands straight in that
        request's TTFT/ITL, so servers and benches call this at startup
        for every bucket the configured workload shape can reach —
        including the resume-tail chunk (a fully prefix-cached prompt
        re-prefills only its final token, chunk_len=1).

        The allocator hands out low block ids first; this sweep claims
        the top ids and requires, per entry, the pool to be at least
        twice the claimed range plus slack — entries too big for the pool
        are skipped individually (with a warning) rather than risk
        overwriting live cached K/V.
        """
        bs = self.block_size
        nb = self.num_blocks
        n = 0
        for chunk_len, total in singles:
            bp = (total + bs - 1) // bs
            if nb < 2 * bp + 64:
                logger.warning(
                    "prefill precompile: skipping single (%d, %d) — pool "
                    "of %d blocks too small", chunk_len, total, nb,
                )
                continue
            self.prefill(
                [1] * chunk_len,
                total - chunk_len,
                list(range(nb - bp, nb)),
                total,
            )
            n += 1
        for s, chunk_len, total in groups:
            bp = (total + bs - 1) // bs
            if nb < 2 * s * bp + 64:
                logger.warning(
                    "prefill precompile: skipping group (%d, %d, %d) — "
                    "pool of %d blocks too small", s, chunk_len, total, nb,
                )
                continue
            tabs = [
                list(range(nb - (i + 1) * bp, nb - i * bp))
                for i in range(s)
            ]
            self.prefill_batch(
                [[1] * chunk_len] * s,
                start_positions=[total - chunk_len] * s,
                block_tables=tabs,
                total_lens=[total] * s,
            )
            n += 1
        return n

    def precompile_decode(
        self, context_lens: list[int], steps: int,
        chained: bool = False,
        stop: bool = False,
    ) -> int:
        """Compile the fused-K decode program for every ctx bucket the
        given context lengths reach, against trash blocks at the top of
        the pool (same safety contract as precompile_prefill). Decode
        lanes are statically padded to max_num_seqs, so the ctx bucket is
        the only shape dimension a serving run crosses mid-stream —
        e.g. multi-round chat sessions grow past a pow2 block-count
        boundary and would otherwise pay an XLA compile inside a live
        ITL measurement. Greedy sampling arrays select the same program
        as any temperature (sampling params are runtime operands).

        `chained=True` additionally compiles the async-pipeline variant
        (device-array token input — a DISTINCT program cache key): the
        chained dispatch crosses the same ctx buckets mid-pipeline, so
        async serving needs both programs warm.

        `stop=True` compiles the device-stop (elastic) program variant
        instead of the fixed-trip scan, at stop-id cap 0 — the cap only
        grows when a request ships stop_token_ids, which is
        request-dependent and out of precompile scope (same caveat as
        the penalties/logprobs variants)."""
        b = self.config.max_num_seqs
        bs = self.block_size
        nb = self.num_blocks
        temps = np.zeros((b,), np.float32)
        top_ps = np.ones((b,), np.float32)
        top_ks = np.full((b,), -1, np.int32)
        keys = np.zeros((b, 2), np.uint32)
        seen: set[int] = set()
        n = 0
        for cl in context_lens:
            c_pad = self._ctx_bucket(cl + max(0, steps - 1))
            if c_pad in seen:
                continue
            seen.add(c_pad)
            npages = c_pad // bs
            # same 2x-plus-slack rule as precompile_prefill: the low
            # half of the pool may already hold live/cached K/V (warmup
            # runs before precompile in bench/server startup), and the
            # trash table must never reach down into it
            if nb < 2 * npages + 64:
                logger.warning(
                    "decode precompile: skipping ctx %d — pool of %d "
                    "blocks too small", cl, nb,
                )
                continue
            # every lane shares one trash table: decode writes land in
            # the same top-of-pool slots, never on live cached K/V
            table = list(range(nb - npages, nb))
            ctx = c_pad - max(0, steps - 1)
            if steps > 1:
                stop_kw = {}
                if stop:
                    # budget == steps: nothing freezes, the while_loop
                    # runs its full trip — the PROGRAM is identical to
                    # what a live batch with real budgets selects
                    stop_kw = {"stop": (
                        np.full((b,), -1, np.int32),
                        np.zeros((b,), np.int32),
                        np.full((b,), steps, np.int32),
                        None,
                    )}
                out = self.decode_multi(
                    [1] * b, [ctx - 1] * b, [table] * b, [ctx] * b,
                    steps, temps, top_ps, top_ks, keys, **stop_kw,
                )
                jax.block_until_ready(out)
                toks = out[0] if isinstance(out, tuple) else out
                n += 1
                if chained:
                    out = self.decode_multi(
                        toks[-1], [ctx - 1] * b, [table] * b, [ctx] * b,
                        steps, temps, top_ps, top_ks, keys, **stop_kw,
                    )
                    jax.block_until_ready(out)
                    n += 1
            else:
                out = self.decode(
                    [1] * b, [ctx - 1] * b, [table] * b, [ctx] * b
                )
                jax.block_until_ready(out)
                n += 1
        return n

    def precompile_verify(
        self, context_lens: list[int], draft_len: int, max_lanes: int
    ) -> int:
        """Compile the packed spec-decode verify programs (program key
        (s_pad, t_pad, c_pad), see verify_batch): every pow2 lane count
        up to max_lanes x the draft-chunk bucket x each ctx bucket,
        against trash blocks at the top of the pool (same safety rule
        as the other precompiles)."""
        bs = self.block_size
        nb = self.num_blocks
        lanes: list[int] = []
        s = 1
        while s <= max_lanes:
            lanes.append(s)
            s *= 2
        seen: set[tuple] = set()
        n = 0
        for cl in context_lens:
            c_pad = self._ctx_bucket(cl)
            npages = c_pad // bs
            for s in lanes:
                key = (s, self._prefill_bucket(draft_len), c_pad)
                if key in seen:
                    continue
                seen.add(key)
                if nb < 2 * s * npages + 64:
                    logger.warning(
                        "verify precompile: skipping s=%d ctx=%d — pool "
                        "of %d blocks too small", s, c_pad, nb,
                    )
                    continue
                tabs = [
                    list(range(nb - (i + 1) * npages, nb - i * npages))
                    for i in range(s)
                ]
                row_sampling = (
                    np.zeros((s,), np.float32),
                    np.ones((s,), np.float32),
                    np.full((s,), -1, np.int32),
                    np.zeros((s,), np.float32),
                    np.zeros((s,), np.uint32),
                    np.zeros((s,), np.int64),
                )
                out = self.verify_batch(
                    [[1] * draft_len] * s,
                    [c_pad - draft_len] * s,
                    tabs,
                    [c_pad] * s,
                    row_sampling,
                )
                jax.block_until_ready(out)
                n += 1
        return n

    # stackcheck: hot-path — dispatch-only: returns device logits without
    # waiting; the caller's sampler owns the one fetch per round
    def decode(
        self,
        token_ids: list[int],
        positions: list[int],
        block_tables: list[list[int]],
        context_lens: list[int],
        lora_slots: list[int] | None = None,
    ) -> jax.Array:
        """One decode step for a batch; returns fp32 logits (b, vocab) where
        rows beyond len(token_ids) are padded lanes."""
        b_actual = len(token_ids)
        b = self.config.max_num_seqs
        c_pad = self._ctx_bucket(max(context_lens))

        tokens = np.zeros((b,), dtype=np.int32)
        tokens[:b_actual] = token_ids
        pos = np.zeros((b,), dtype=np.int32)
        pos[:b_actual] = positions
        ctx = np.ones((b,), dtype=np.int32)
        ctx[:b_actual] = context_lens

        write_slots = np.zeros((b,), dtype=np.int32)
        for i in range(b_actual):
            write_slots[i] = self._slots_for_positions(
                block_tables[i], np.asarray([positions[i]])
            )[0]
        if self.attention_impl == "pallas":
            # pallas path takes padded block tables (pages), not per-token
            # gather slots
            n_pages = c_pad // self.block_size
            tables = np.stack(
                [
                    self._padded_block_table(
                        block_tables[i] if i < b_actual else [], n_pages
                    )
                    for i in range(b)
                ]
            )
        else:
            tables = np.zeros((b, c_pad), dtype=np.int32)
            for i in range(b_actual):
                tables[i] = self._gather_slots_for_table(
                    block_tables[i], c_pad
                )

        key = (b, c_pad)
        if key not in self._decode_fns:
            logger.info("compiling decode step b=%d ctx=%d", b, c_pad)
            self._note_compile("decode")
            self._decode_fns[key] = self._build_decode(b, c_pad)
        fn = self._decode_fns[key]
        lora_kw = {}
        if self.lora_manager is not None:
            slots = np.zeros((b,), dtype=np.int32)
            if lora_slots is not None:
                slots[:b_actual] = lora_slots
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.asarray(slots),
            }
        logits, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(pos),
            jnp.asarray(write_slots),
            jnp.asarray(tables),
            jnp.asarray(ctx),
            **lora_kw,
        )
        return logits

    def _fill_decode_pack(
        self,
        c_pad: int,
        chained: bool,
        token_ids,
        positions,
        block_tables,
        context_lens,
        temps, top_ps, top_ks, keys,
        min_ps=None,
        guided_lanes: tuple | None = None,
        stop: tuple | None = None,
    ) -> np.ndarray:
        """Build the ONE packed int32 host buffer a fused decode
        dispatch ships (layout: _decode_pack_layout). Shared by the
        dispatch path (decode_multi) and the speculative prefetch path
        (stage_decode_multi). `stop` = (eos, min_rem, budget,
        stop_ids|None) per-lane device-stop arrays (see decode_multi);
        padded lanes ship eos -1 and budget 0 (frozen from iteration
        0, so all-real-lanes-done rounds early-exit)."""
        b = self.config.max_num_seqs
        b_actual = len(positions)
        stop_cap = None
        if stop is not None:
            stop_cap = 0 if stop[3] is None else int(stop[3].shape[1])
        layout, total = self._decode_pack_layout(
            b, c_pad, chained, guided=guided_lanes is not None,
            stop_cap=stop_cap,
        )
        packed = np.zeros((total,), np.int32)

        def put(name, arr):
            off, shape = layout[name]
            n = int(np.prod(shape))
            packed[off:off + n] = arr.reshape(-1).view(np.int32)

        if not chained:
            tokens = np.zeros((b,), dtype=np.int32)
            tokens[:b_actual] = token_ids
            put("tokens", tokens)
        pos = np.zeros((b,), dtype=np.int32)
        pos[:b_actual] = positions
        put("positions", pos)
        ctx = np.ones((b,), dtype=np.int32)
        ctx[:b_actual] = context_lens
        put("ctx", ctx)

        n_pages = c_pad // self.block_size
        page_tables = np.stack(
            [
                self._padded_block_table(
                    block_tables[i] if i < b_actual else [], n_pages
                )
                for i in range(b)
            ]
        )
        put("page_tables", page_tables)
        if self.attention_impl != "pallas":
            gather_tables = np.zeros((b, c_pad), dtype=np.int32)
            for i in range(b_actual):
                gather_tables[i] = self._gather_slots_for_table(
                    block_tables[i], c_pad
                )
            put("gather_tables", gather_tables)

        t_full = np.zeros((b,), np.float32)
        t_full[:b_actual] = temps
        put("temps", t_full)
        p_full = np.ones((b,), np.float32)
        p_full[:b_actual] = top_ps
        put("top_ps", p_full)
        k_full = np.full((b,), -1, np.int32)
        k_full[:b_actual] = top_ks
        put("top_ks", k_full)
        m_full = np.zeros((b,), np.float32)
        if min_ps is not None:
            m_full[:b_actual] = min_ps
        put("min_ps", m_full)
        key_full = np.zeros((b, 2), np.uint32)
        key_full[:b_actual] = keys
        put("keys", key_full)
        if guided_lanes is not None:
            init_states, lane_map = guided_lanes
            g_state = np.zeros((b,), np.int32)
            g_state[:b_actual] = init_states[:b_actual]
            put("g_state", g_state)
            g_lane = np.zeros((b,), np.int32)
            g_lane[:b_actual] = lane_map[:b_actual]
            put("g_lane", g_lane)
        if stop is not None:
            eos, min_rem, budget, stop_ids = stop
            eos_full = np.full((b,), -1, np.int32)
            eos_full[:b_actual] = eos
            put("stop_eos", eos_full)
            min_full = np.zeros((b,), np.int32)
            min_full[:b_actual] = min_rem
            put("stop_min", min_full)
            bud_full = np.zeros((b,), np.int32)  # padded lanes: done
            bud_full[:b_actual] = budget
            put("stop_budget", bud_full)
            if stop_cap:
                sid_full = np.full((b, stop_cap), -1, np.int32)
                sid_full[:b_actual] = stop_ids
                put("stop_ids", sid_full)
        return packed

    # stackcheck: hot-path
    def stage_decode_multi(
        self, positions, block_tables, context_lens, steps,
        temps, top_ps, top_ks, keys, min_ps=None, stop=None,
    ):
        """Speculative h2d prefetch for the NEXT chained fused round:
        build the packed buffer and START its async host->device
        transfer now, so the upload overlaps the in-flight round's
        execution and token fetch instead of sitting serially between
        them (measured ~116 ms per h2d vs ~300 ms total round time
        through the tunneled chip). The engine stages with PREDICTED
        state (positions/ctx/keys — and, under device stops, the
        min_rem/budget countdowns — advanced by K on the same lanes)
        and validates the prediction before dispatching on it; a stale
        stage (ctx-bucket mismatch) is ignored by decode_multi.
        Returns (c_pad, device_array) for decode_multi(staged=...)."""
        c_pad = self._ctx_bucket(max(context_lens) + max(0, steps - 1))
        packed = self._fill_decode_pack(
            c_pad, True, None, positions, block_tables, context_lens,
            temps, top_ps, top_ks, keys, min_ps=min_ps, stop=stop,
        )
        return (c_pad, jax.device_put(packed))

    def _decode_pen_kwargs(
        self, penalties: tuple | None, b: int, c_pad: int, b_actual: int
    ) -> dict:
        """Device penalty-state args for the fused decode scan, shared
        by decode_multi and ragged_dispatch."""
        if penalties is None:
            return {}
        gen_lists, presence, frequency, repetition = penalties
        # pad the generated-id history to c_pad (generated tokens are
        # part of the context, so it always fits): gen shape then
        # varies only with the existing ctx bucket — a separate pow2
        # gen bucket would multiply the compile space mid-serving
        gen_full = np.full((b, c_pad), -1, np.int32)
        for i, g in enumerate(gen_lists):
            gen_full[i, : len(g)] = g
        pres_full = np.zeros((b,), np.float32)
        pres_full[:b_actual] = presence
        freq_full = np.zeros((b,), np.float32)
        freq_full[:b_actual] = frequency
        rep_full = np.ones((b,), np.float32)
        rep_full[:b_actual] = repetition
        return {
            "gen_ids": jnp.asarray(gen_full),
            "presence": jnp.asarray(pres_full),
            "frequency": jnp.asarray(freq_full),
            "repetition": jnp.asarray(rep_full),
        }

    def _decode_guided_kwargs(
        self, guided: tuple | None
    ) -> tuple[dict, tuple | None]:
        """Device TokenDFA-table args (+ static shapes) for the fused
        decode scan, shared by decode_multi and ragged_dispatch."""
        if guided is None:
            return {}, None
        # per-lane g_state/g_lane were packed by _fill_decode_pack
        (g_token, init_states, lane_map, token_class, class_mask,
         class_trans) = guided
        # device-cache the big tables across dispatches: they change
        # only when the set of live constraints changes
        cached = getattr(self, "_guided_dev", None)
        if cached is None or cached[0] != g_token:
            self._guided_dev = (
                g_token,
                jnp.asarray(token_class),
                jnp.asarray(class_mask),
                jnp.asarray(class_trans),
            )
        _, tc_dev, mask_dev, trans_dev = self._guided_dev
        guided_kw = {
            "g_token_class": tc_dev,
            "g_class_mask": mask_dev,
            "g_class_trans": trans_dev,
        }
        guided_shapes = (
            token_class.shape[0], class_mask.shape[0],
            class_mask.shape[1],
        )
        return guided_kw, guided_shapes

    def _decode_bias_kwargs(
        self, logit_bias: tuple | None, b: int, b_actual: int
    ) -> tuple[dict, int]:
        """Dense logit-bias args (+ cap) for the fused decode scan,
        shared by decode_multi and ragged_dispatch."""
        if logit_bias is None:
            return {}, 0
        lb_ids, lb_vals = logit_bias  # (b_actual, cap) ndarrays
        bias_cap = int(lb_ids.shape[1])
        ids_full = np.zeros((b, bias_cap), np.int32)
        vals_full = np.zeros((b, bias_cap), np.float32)
        ids_full[:b_actual] = lb_ids
        vals_full[:b_actual] = lb_vals
        return {
            "lb_ids": jnp.asarray(ids_full),
            "lb_vals": jnp.asarray(vals_full),
        }, bias_cap

    # stackcheck: hot-path — one dispatch, one deferred fetch; a stray
    # sync forcer here costs a full RTT per decode round
    def decode_multi(
        self,
        token_ids: list[int],
        positions: list[int],
        block_tables: list[list[int]],
        context_lens: list[int],
        steps: int,
        temps: np.ndarray,      # (b_actual,) float32
        top_ps: np.ndarray,
        top_ks: np.ndarray,
        keys: np.ndarray,       # (b_actual, 2) uint32
        min_ps: np.ndarray | None = None,  # (b_actual,) f32; None => off
        lora_slots: list[int] | None = None,
        penalties: tuple | None = None,
        want_logprobs: bool = False,
        guided: tuple | None = None,
        logit_bias: tuple | None = None,  # ((b_actual, cap) i32 ids,
                                          #  (b_actual, cap) f32 vals)
        staged: tuple | None = None,  # pre-uploaded (c_pad, packed_dev)
                                      # from stage_decode_multi
        stop: tuple | None = None,  # device-side stop masks: (eos
                                    # (b_actual,) i32 — -1 = ignore,
                                    # min_rem (b_actual,) i32,
                                    # budget (b_actual,) i32,
                                    # stop_ids (b_actual, cap) i32
                                    # padded -1, or None)
    ):
        """`steps` fused decode+sample iterations (one dispatch, one
        fetch); returns (steps, b) int32 sampled tokens on device — or,
        with `want_logprobs`, a tuple (tokens, chosen_lp (k, b) f32,
        top_vals (k, b, CAP) f32, top_ids (k, b, CAP) i32). With
        `stop` (device-side stop masks, see _build_decode_multi) the
        return is ALWAYS a tuple whose last element is the (b,) int32
        per-lane valid count — (tokens, valid) or (tokens, chosen_lp,
        top_vals, top_ids, valid); tokens at rows >= valid[lane] are
        pinned pad, the round early-exits once every lane is done, and
        the caller applies exactly valid[lane] tokens per lane. The
        caller must have grown each block table to cover
        context_len + steps - 1 positions (scheduler lookahead).

        `penalties`: optional (gen_ids_list, presence, frequency,
        repetition) — generated-token history per lane (list of int
        lists) + (b_actual,) penalty arrays; token counts are then
        maintained on device through the scan (sampler.apply_penalties
        semantics, bit-identical to the host single-step path).

        `token_ids` may be a full-lane (b,) DEVICE array instead of a
        host list: the async-decode pipeline chains round N+1 directly on
        round N's on-device sampled tokens, so no host fetch sits between
        dispatches.

        `guided`: optional (cache_token, init_states (b,), lane_map (b,),
        token_class (M, V), class_mask (S, C), class_trans (S, C)) —
        TokenDFA tables (engine/structured.py) evaluated INSIDE the
        fused scan so constrained lanes keep the K-step fetch
        amortization. The three big tables are uploaded once per
        `cache_token` and reused across dispatches."""
        if steps > self.block_size:
            raise ValueError(
                f"num_scheduler_steps={steps} > block_size="
                f"{self.block_size}: idle lanes would overrun the trash "
                "block"
            )
        b = self.config.max_num_seqs
        chained = isinstance(token_ids, jax.Array)
        b_actual = len(positions) if chained else len(token_ids)
        c_pad = self._ctx_bucket(max(context_lens) + steps - 1)

        # ONE packed i32 host->device buffer per dispatch (layout shared
        # with the jitted unpack, _decode_pack_layout): through the
        # tunneled chip each separate buffer creation pays link latency.
        # A valid speculative stage (stage_decode_multi) skips the build
        # AND the serial upload entirely — its transfer overlapped the
        # previous round.
        guided_lanes = None
        if guided is not None:
            guided_lanes = (guided[1], guided[2])
        stop_cap = None
        if stop is not None:
            stop_cap = 0 if stop[3] is None else int(stop[3].shape[1])
        packed_dev = None
        if (staged is not None and chained and guided is None
                and staged[0] == c_pad):
            # the staged buffer must carry the SAME field layout this
            # dispatch expects — the stop fields vary with the per-batch
            # stop-id cap, so a total-length mismatch is a stale stage
            # (rebuild + upload serially), never a dispatch error
            _, want_total = self._decode_pack_layout(
                b, c_pad, chained, guided=False, stop_cap=stop_cap,
            )
            if int(staged[1].shape[0]) == want_total:
                packed_dev = staged[1]
        if packed_dev is None:
            packed_dev = jnp.asarray(self._fill_decode_pack(
                c_pad, chained, token_ids, positions, block_tables,
                context_lens, temps, top_ps, top_ks, keys,
                min_ps=min_ps, guided_lanes=guided_lanes, stop=stop,
            ))

        pen_kw = self._decode_pen_kwargs(penalties, b, c_pad, b_actual)
        guided_kw, guided_shapes = self._decode_guided_kwargs(guided)
        bias_kw, bias_cap = self._decode_bias_kwargs(
            logit_bias, b, b_actual
        )
        cache_key = (b, c_pad, steps, penalties is not None,
                     want_logprobs, chained, guided_shapes, bias_cap,
                     stop_cap)
        if cache_key not in self._decode_multi_fns:
            logger.info(
                "compiling multi-step decode b=%d ctx=%d k=%d pen=%s "
                "lp=%s chained=%s guided=%s bias=%d stop=%s",
                b, c_pad, steps, penalties is not None, want_logprobs,
                chained, guided_shapes, bias_cap, stop_cap,
            )
            self._note_compile("decode_multi")
            self._decode_multi_fns[cache_key] = self._build_decode_multi(
                b, c_pad, steps, use_penalties=penalties is not None,
                want_logprobs=want_logprobs, chained=chained,
                guided_shapes=guided_shapes, bias_cap=bias_cap,
                stop_cap=stop_cap,
            )
        fn = self._decode_multi_fns[cache_key]
        lora_kw = {}
        if self.lora_manager is not None:
            slots = np.zeros((b,), dtype=np.int32)
            if lora_slots is not None:
                slots[:b_actual] = lora_slots
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.asarray(slots),
            }
        chained_kw = {"chained_tokens": token_ids} if chained else {}
        ys, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            packed_dev,
            **chained_kw,
            **guided_kw,
            **pen_kw,
            **bias_kw,
            **lora_kw,
        )
        return ys

    # -- unified ragged prefill+decode dispatch ----------------------------
    # ONE lane-typed engine round: a single packed h2d buffer whose lanes
    # mix prefill chunks and decode steps (Ragged Paged Attention role,
    # PAPERS.md), one jitted program that runs the prefill lanes' chunk
    # attention and the decode lanes' stop-aware scan back to back. The
    # two lane sets belong to DIFFERENT sequences with disjoint block
    # tables, so the in-program ordering cannot change any sampled value:
    # tokens are bit-identical to a split prefill round followed by a
    # decode round (tests/test_ragged_dispatch.py pins it).

    def _ragged_pack_sizes(
        self, s_pad: int, t_pad: int, pc_pad: int, b: int, c_pad: int,
        chained: bool, guided: bool = False, stop_cap: int | None = None,
    ) -> tuple[int, int, int]:
        """(meta, prefill, decode) segment lengths of the ONE packed i32
        buffer a ragged dispatch ships: a lane-meta header (per-lane
        type/length/budget — the fields extending _decode_pack_layout
        to a lane-typed round), then the packed prefill pack, then the
        decode pack, concatenated. The decode segment varies with the
        stop-id cap and guided fields exactly like _decode_pack_layout,
        so a staged buffer whose total length mismatches the dispatch's
        expectation is a STALE STAGE (counted miss), never an error."""
        meta = 3 * (s_pad + b)
        _, pf = self._packed_prefill_pack_layout(s_pad, t_pad, pc_pad)
        _, dec = self._decode_pack_layout(
            b, c_pad, chained, guided=guided, stop_cap=stop_cap
        )
        return meta, pf, dec

    # stackcheck: hot-path — host build of the ragged round's single
    # h2d buffer, shared by the dispatch and the staging prefetch; one
    # pass over the lanes, no device fetch
    def _fill_ragged_pack(
        self,
        pf_chunks: list[list[int]],
        pf_start_positions: list[int],
        pf_block_tables: list[list[int]],
        pf_total_lens: list[int],
        pf_sampling,
        c_pad: int,
        chained: bool,
        token_ids,
        positions,
        block_tables,
        context_lens,
        steps: int,
        temps, top_ps, top_ks, keys,
        min_ps=None,
        guided_lanes: tuple | None = None,
        stop: tuple | None = None,
        pf_budgets: list[int] | None = None,
        dec_budgets: list[int] | None = None,
    ) -> tuple[int, int, int, np.ndarray]:
        """Concatenate lane-meta + prefill pack + decode pack; returns
        (s_pad, t_pad, pc_pad, packed). Lane order: prefill lanes 0..n_pf
        (padded to s_pad), then the b decode lanes. `lane_budgets` carry
        remaining prompt tokens (prefill lanes) / remaining token budget
        (decode lanes) — self-describing for debugging, and lane_types
        gates the device-side idle-lane token pinning."""
        b = self.config.max_num_seqs
        s_pad, t_pad, pc_pad, pf_packed = self._fill_packed_prefill_pack(
            pf_chunks, pf_start_positions, pf_block_tables,
            pf_total_lens, sampling=pf_sampling,
        )
        dec_packed = self._fill_decode_pack(
            c_pad, chained, token_ids, positions, block_tables,
            context_lens, temps, top_ps, top_ks, keys, min_ps=min_ps,
            guided_lanes=guided_lanes, stop=stop,
        )
        n_pf = len(pf_chunks)
        n_dec = len(positions)
        n_lanes = s_pad + b
        types = np.zeros((n_lanes,), np.int32)
        types[:n_pf] = RAGGED_LANE_PREFILL
        types[s_pad:s_pad + n_dec] = RAGGED_LANE_DECODE
        lens = np.zeros((n_lanes,), np.int32)
        lens[:n_pf] = [len(c) for c in pf_chunks]
        lens[s_pad:s_pad + n_dec] = steps
        budgets = np.zeros((n_lanes,), np.int32)
        if pf_budgets is not None:
            budgets[:n_pf] = pf_budgets
        if dec_budgets is not None:
            budgets[s_pad:s_pad + n_dec] = dec_budgets
        elif stop is not None:
            budgets[s_pad:s_pad + n_dec] = stop[2]
        packed = np.concatenate([types, lens, budgets, pf_packed,
                                 dec_packed])
        return s_pad, t_pad, pc_pad, packed

    def _build_ragged(self, s_pad: int, t_pad: int, pc_pad: int,
                      b: int, c_pad: int, k_steps: int,
                      use_penalties: bool = False,
                      want_logprobs: bool = False,
                      chained: bool = False,
                      guided_shapes: tuple | None = None,
                      bias_cap: int = 0,
                      stop_cap: int | None = None):
        """ONE jitted lane-typed round: unpack the fused buffer's three
        segments, run the packed prefill step over the prefill lanes,
        then the fused decode scan over the decode lanes — one h2d
        transfer, one dispatch enqueue, and the decode half's device
        stop masks / penalties / guided tables unchanged from
        _make_decode_multi_step. Idle prefill lanes' sampled slots are
        pinned to sampler.RAGGED_IDLE_TOKEN from the lane-meta header so
        the host can assert it only consumes real lanes."""
        from production_stack_tpu.engine.sampler import RAGGED_IDLE_TOKEN

        pf_step = self._make_prefill_batch_packed(s_pad, t_pad, pc_pad)
        dec_step = self._make_decode_multi_step(
            b, c_pad, k_steps, use_penalties=use_penalties,
            want_logprobs=want_logprobs, chained=chained,
            guided_shapes=guided_shapes, bias_cap=bias_cap,
            stop_cap=stop_cap,
        )
        meta_n, pf_n, _dec_n = self._ragged_pack_sizes(
            s_pad, t_pad, pc_pad, b, c_pad, chained,
            guided=guided_shapes is not None, stop_cap=stop_cap,
        )

        def step(params, kc, vc, packed, chained_tokens=None,
                 g_token_class=None, g_class_mask=None,
                 g_class_trans=None, gen_ids=None, presence=None,
                 frequency=None, repetition=None, lb_ids=None,
                 lb_vals=None, lora=None, lora_slots=None,
                 pf_lora_slots=None):
            lane_types = packed[:s_pad + b]
            pf_packed = packed[meta_n:meta_n + pf_n]
            dec_packed = packed[meta_n + pf_n:]
            # prefill lanes first: their chunk K/V lands before the
            # decode scan runs, matching the split path's round order
            # (values are order-independent anyway — disjoint tables)
            pf_sampled, pf_logits, kc, vc = pf_step(
                params, kc, vc, pf_packed, lora=lora,
                lora_slots=pf_lora_slots,
            )
            ys, kc, vc = dec_step(
                params, kc, vc, dec_packed,
                chained_tokens=chained_tokens,
                g_token_class=g_token_class, g_class_mask=g_class_mask,
                g_class_trans=g_class_trans, gen_ids=gen_ids,
                presence=presence, frequency=frequency,
                repetition=repetition, lb_ids=lb_ids, lb_vals=lb_vals,
                lora=lora, lora_slots=lora_slots,
            )
            pf_sampled = jnp.where(
                lane_types[:s_pad] == RAGGED_LANE_PREFILL,
                pf_sampled, RAGGED_IDLE_TOKEN,
            )
            return pf_sampled, pf_logits, ys, kc, vc

        return jax.jit(step, donate_argnums=(1, 2))

    # -- single-kernel ragged-rows round -----------------------------------
    def _ragged_rows_pack_sizes(
        self, r_pad: int, pc_pad: int, b: int, c_pad: int,
        chained: bool, guided: bool = False,
        stop_cap: int | None = None,
    ) -> tuple[int, int, int]:
        """(meta, prefill, decode) segment lengths of the ragged-ROWS
        round's packed buffer (kernel-mode mirror of
        _ragged_pack_sizes: the prefill segment is the ragged-rows
        pack, lane meta spans the static lane cap)."""
        meta = 3 * (self._rows_lane_cap() + b)
        _, pf = self._rows_prefill_pack_layout(r_pad, pc_pad)
        _, dec = self._decode_pack_layout(
            b, c_pad, chained, guided=guided, stop_cap=stop_cap
        )
        return meta, pf, dec

    # stackcheck: hot-path — host build of the kernel-mode round's
    # single h2d buffer (dispatch + staging prefetch); one pass over
    # the lanes, no device fetch
    def _fill_ragged_rows_pack(
        self,
        pf_chunks, pf_start_positions, pf_block_tables, pf_total_lens,
        pf_sampling, c_pad, chained, token_ids, positions,
        block_tables, context_lens, steps, temps, top_ps, top_ks,
        keys, min_ps=None, guided_lanes=None, stop=None,
        pf_budgets=None, dec_budgets=None,
    ) -> tuple[int, int, np.ndarray]:
        """Kernel-mode mirror of _fill_ragged_pack: lane-meta header
        (lane cap + b lanes) + the ragged-ROWS prefill pack + the
        decode pack. Returns (r_pad, pc_pad, packed)."""
        b = self.config.max_num_seqs
        s_cap = self._rows_lane_cap()
        r_pad, pc_pad, pf_packed = self._fill_rows_prefill_pack(
            pf_chunks, pf_start_positions, pf_block_tables,
            pf_total_lens, sampling=pf_sampling,
        )
        dec_packed = self._fill_decode_pack(
            c_pad, chained, token_ids, positions, block_tables,
            context_lens, temps, top_ps, top_ks, keys, min_ps=min_ps,
            guided_lanes=guided_lanes, stop=stop,
        )
        n_pf = len(pf_chunks)
        n_dec = len(positions)
        n_lanes = s_cap + b
        types = np.zeros((n_lanes,), np.int32)
        types[:n_pf] = RAGGED_LANE_PREFILL
        types[s_cap:s_cap + n_dec] = RAGGED_LANE_DECODE
        lens = np.zeros((n_lanes,), np.int32)
        lens[:n_pf] = [len(c) for c in pf_chunks]
        lens[s_cap:s_cap + n_dec] = steps
        budgets = np.zeros((n_lanes,), np.int32)
        if pf_budgets is not None:
            budgets[:n_pf] = pf_budgets
        if dec_budgets is not None:
            budgets[s_cap:s_cap + n_dec] = dec_budgets
        elif stop is not None:
            budgets[s_cap:s_cap + n_dec] = stop[2]
        packed = np.concatenate([types, lens, budgets, pf_packed,
                                 dec_packed])
        return r_pad, pc_pad, packed

    def _build_ragged_rows(self, r_pad: int, pc_pad: int, b: int,
                           c_pad: int, k_steps: int,
                           use_penalties: bool = False,
                           want_logprobs: bool = False,
                           chained: bool = False,
                           guided_shapes: tuple | None = None,
                           bias_cap: int = 0,
                           stop_cap: int | None = None):
        """ONE jitted lane-typed round in single-kernel mode: the
        prefill lanes' chunk rows AND the decode lanes' step-0 query
        rows share one flattened row space — one forward pass whose
        per-layer attention is ONE ragged_paged_attention launch over
        the whole lane mix — then decode iterations 1..K-1 continue
        through the shared decode core (the same kernel in all-decode
        configuration). Prefill and decode lanes belong to different
        sequences with disjoint block tables, and the decode half's
        post-sample math is _decode_round_core's verbatim, so tokens
        and logical KV are bit-identical to both the composed-kernel
        ragged round and the split path."""
        from production_stack_tpu.engine.sampler import (
            RAGGED_IDLE_TOKEN,
            sample_tokens,
        )

        mc = self.model_config
        tq = RAGGED_TQ
        bs = self.block_size
        s_cap = self._rows_lane_cap()
        b_pad = _ceil_tq(b)
        pf_step = self._make_prefill_rows_step(r_pad, pc_pad)
        pf_unpack = pf_step._unpack
        core = self._decode_round_core(
            b, c_pad, k_steps, use_penalties=use_penalties,
            want_logprobs=want_logprobs, chained=chained,
            guided_shapes=guided_shapes, bias_cap=bias_cap,
            stop_cap=stop_cap,
        )
        meta_n, pf_n, _dec_n = self._ragged_rows_pack_sizes(
            r_pad, pc_pad, b, c_pad, chained,
            guided=guided_shapes is not None, stop_cap=stop_cap,
        )
        n_pages = max(pc_pad, c_pad) // bs
        n_pf_blk = r_pad // tq
        n_dec_blk = b_pad // tq

        def step(params, kc, vc, packed, chained_tokens=None,
                 g_token_class=None, g_class_mask=None,
                 g_class_trans=None, gen_ids=None, presence=None,
                 frequency=None, repetition=None, lb_ids=None,
                 lb_vals=None, lora=None, lora_slots=None,
                 pf_lora_slots=None):
            kc, vc = self._pin_cache_layout(kc, vc)
            lane_types = packed[:s_cap + b]
            pf_packed = packed[meta_n:meta_n + pf_n]
            dec_packed = packed[meta_n + pf_n:]
            pf = pf_unpack(pf_packed)
            consts, carry0 = core["unpack"](
                dec_packed, chained_tokens=chained_tokens,
                g_token_class=g_token_class, g_class_mask=g_class_mask,
                g_class_trans=g_class_trans, gen_ids=gen_ids,
                presence=presence, frequency=frequency,
                repetition=repetition, lb_ids=lb_ids, lb_vals=lb_vals,
            )
            # fused step-0 forward over [prefill rows | decode rows]:
            # decode write slots / ctx come from the shared core so
            # frozen-lane trash redirection matches the loop's
            d_tokens, d_positions, d_ws, d_ctx = core["fwd_args"](
                carry0, consts
            )
            tokens_cat = jnp.concatenate([pf["tokens"], d_tokens])
            positions_cat = jnp.concatenate(
                [pf["positions"], d_positions]
            )
            ws_cat = jnp.concatenate([pf["write_slots"], d_ws])
            # lane tables: prefill lanes then decode lanes, padded to
            # the wider page count (pad pages point at the null block
            # and sit beyond every segment's page walk)
            pf_tab = pf["tables"]
            dec_tab = consts["page_tables"]
            pf_tab = jnp.pad(
                pf_tab, ((0, 0), (0, n_pages - pf_tab.shape[1]))
            )
            dec_tab = jnp.pad(
                dec_tab, ((0, 0), (0, n_pages - dec_tab.shape[1]))
            )
            tables_cat = jnp.concatenate([pf_tab, dec_tab], axis=0)
            # block map: prefill blocks carry one chunk segment each;
            # decode lanes are single-row segments sharing the tail
            # blocks (q_pos = ctx-1 makes decode the degenerate causal
            # case of the one kernel body)
            pf_seg = self._rows_pf_seg_meta(
                r_pad, pf["lane_row0"], pf["lane_rows"], pf["q_starts"]
            )
            dlanes = jnp.arange(b, dtype=jnp.int32)
            dec_seg = jnp.stack([
                s_cap + dlanes,
                dlanes % tq,
                jnp.ones((b,), jnp.int32),
                d_ctx - 1,
            ], axis=1)
            seg_meta = jnp.concatenate([pf_seg, dec_seg], axis=0)
            blk_seg = jnp.concatenate([
                jnp.arange(n_pf_blk + 1, dtype=jnp.int32),
                n_pf_blk + jnp.minimum(
                    (jnp.arange(n_dec_blk, dtype=jnp.int32) + 1) * tq,
                    b,
                ),
            ])

            def attn_fn(q, l, kcc, vcc):
                qp = jnp.pad(q, ((0, b_pad - b), (0, 0), (0, 0)))
                out = self._attn(
                    "ragged", qp, l, kcc, vcc, tables_cat, blk_seg,
                    seg_meta,
                )
                return out[:r_pad + b]

            lora_cat = None
            if lora is not None:
                lora_cat = jnp.concatenate([pf_lora_slots, lora_slots])
            logits_all, kc, vc = self._forward(
                mc, params, tokens_cat, positions_cat, kc, vc, ws_cat,
                attn_fn,
                logits_rows=jnp.concatenate(
                    [pf["last_rows"], r_pad + jnp.arange(b)]
                ),
                lora=lora, lora_slots=lora_cat,
            )
            pf_logits = logits_all[:s_cap]
            dec0_logits = logits_all[s_cap:]
            pf_sampled = sample_tokens(
                pf_logits, pf["temps"], pf["top_ps"], pf["top_ks"],
                pf["keys"], min_p=pf["min_ps"],
            )
            pf_sampled = jnp.where(
                lane_types[:s_cap] == RAGGED_LANE_PREFILL,
                pf_sampled, RAGGED_IDLE_TOKEN,
            )
            ys, kc, vc = core["run"](
                params, kc, vc, consts, carry0, lora=lora,
                lora_slots=lora_slots, first_logits=dec0_logits,
            )
            return pf_sampled, pf_logits, ys, kc, vc

        return jax.jit(step, donate_argnums=(1, 2))

    # stackcheck: hot-path — speculative h2d prefetch of the NEXT ragged
    # round's packed buffer: the upload overlaps the in-flight round's
    # execution and fetch (prefill mirror: stage_prefill_batch; decode
    # mirror: stage_decode_multi). Enqueue-only, no device fetch.
    def stage_ragged(
        self,
        pf_chunks: list[list[int]],
        pf_start_positions: list[int],
        pf_block_tables: list[list[int]],
        pf_total_lens: list[int],
        pf_sampling,
        positions, block_tables, context_lens, steps,
        temps, top_ps, top_ks, keys,
        min_ps=None, stop=None,
        pf_budgets=None, dec_budgets=None,
    ) -> tuple:
        """Build + START uploading the predicted next ragged round's
        packed buffer (decode half chained: its tokens ride on device
        from the current round). Returns a handle for
        ragged_dispatch(staged=...); the caller validates its
        fingerprint — and the dispatch validates the total layout
        length — before use."""
        t0 = time.perf_counter()
        c_pad = self._ctx_bucket(
            max(context_lens) + max(0, steps - 1)
        )
        if self.ragged_kernel:
            r_pad, pc_pad, packed = self._fill_ragged_rows_pack(
                pf_chunks, pf_start_positions, pf_block_tables,
                pf_total_lens, pf_sampling, c_pad, True, None,
                positions, block_tables, context_lens, steps, temps,
                top_ps, top_ks, keys, min_ps=min_ps, stop=stop,
                pf_budgets=pf_budgets, dec_budgets=dec_budgets,
            )
            key = ("rows", r_pad, pc_pad, c_pad)
        else:
            s_pad, t_pad, pc_pad, packed = self._fill_ragged_pack(
                pf_chunks, pf_start_positions, pf_block_tables,
                pf_total_lens, pf_sampling, c_pad, True, None,
                positions, block_tables, context_lens, steps, temps,
                top_ps, top_ks, keys, min_ps=min_ps, stop=stop,
                pf_budgets=pf_budgets, dec_budgets=dec_budgets,
            )
            key = ("ragged", s_pad, t_pad, pc_pad, c_pad)
        t1 = time.perf_counter()
        self._phase_add("prep", t1 - t0)
        handle = (key, jax.device_put(packed))
        self._phase_add("h2d", time.perf_counter() - t1)
        return handle

    # stackcheck: hot-path — ONE dispatch serves the whole lane-typed
    # round (prefill chunks + decode steps); fetches stay deferred to
    # the caller, a stray sync forcer here costs a full RTT per round
    def ragged_dispatch(
        self,
        pf_chunks: list[list[int]],
        pf_start_positions: list[int],
        pf_block_tables: list[list[int]],
        pf_total_lens: list[int],
        token_ids,
        positions: list[int],
        block_tables: list[list[int]],
        context_lens: list[int],
        steps: int,
        temps, top_ps, top_ks, keys,
        min_ps=None,
        pf_sampling=None,
        pf_lora_slots: list[int] | None = None,
        lora_slots: list[int] | None = None,
        penalties: tuple | None = None,
        want_logprobs: bool = False,
        guided: tuple | None = None,
        logit_bias: tuple | None = None,
        staged: tuple | None = None,
        stop: tuple | None = None,
        pf_budgets: list[int] | None = None,
        dec_budgets: list[int] | None = None,
    ) -> tuple:
        """One lane-typed engine round: prefill chunk lanes + fused
        decode lanes in a single program. Returns (pf_sampled (s_pad,)
        i32 device — RAGGED_IDLE_TOKEN on non-real lanes, pf_logits
        (s_pad, vocab) device, dec_ys) where dec_ys matches
        decode_multi's return shape for the same flags. `staged` = a
        stage_ragged handle; used only when its bucket key AND total
        layout length match (a lane-mix or stop-cap drift between stage
        and dispatch rebuilds serially — a counted staging miss, never
        a dispatch error)."""
        if steps > self.block_size:
            raise ValueError(
                f"num_scheduler_steps={steps} > block_size="
                f"{self.block_size}: idle lanes would overrun the trash "
                "block"
            )
        if self.ragged_kernel:
            return self._ragged_rows_dispatch(
                pf_chunks, pf_start_positions, pf_block_tables,
                pf_total_lens, token_ids, positions, block_tables,
                context_lens, steps, temps, top_ps, top_ks, keys,
                min_ps=min_ps, pf_sampling=pf_sampling,
                pf_lora_slots=pf_lora_slots, lora_slots=lora_slots,
                penalties=penalties, want_logprobs=want_logprobs,
                guided=guided, logit_bias=logit_bias, staged=staged,
                stop=stop, pf_budgets=pf_budgets,
                dec_budgets=dec_budgets,
            )
        b = self.config.max_num_seqs
        chained = isinstance(token_ids, jax.Array)
        b_actual = len(positions)
        c_pad = self._ctx_bucket(max(context_lens) + steps - 1)
        s_pad = next_pow2(max(len(pf_chunks), 1))
        t_pad = self._prefill_bucket(max(len(c) for c in pf_chunks))
        pc_pad = max(self._ctx_bucket(tl) for tl in pf_total_lens)
        guided_lanes = None
        if guided is not None:
            guided_lanes = (guided[1], guided[2])
        stop_cap = None
        if stop is not None:
            stop_cap = 0 if stop[3] is None else int(stop[3].shape[1])
        packed_dev = None
        if (staged is not None and chained and guided is None
                and staged[0] == ("ragged", s_pad, t_pad, pc_pad,
                                  c_pad)):
            want_total = sum(self._ragged_pack_sizes(
                s_pad, t_pad, pc_pad, b, c_pad, chained,
                guided=False, stop_cap=stop_cap,
            ))
            if int(staged[1].shape[0]) == want_total:
                packed_dev = staged[1]
        if packed_dev is None:
            t0 = time.perf_counter()
            _s, _t, _pc, packed = self._fill_ragged_pack(
                pf_chunks, pf_start_positions, pf_block_tables,
                pf_total_lens, pf_sampling, c_pad, chained, token_ids,
                positions, block_tables, context_lens, steps, temps,
                top_ps, top_ks, keys, min_ps=min_ps,
                guided_lanes=guided_lanes, stop=stop,
                pf_budgets=pf_budgets, dec_budgets=dec_budgets,
            )
            t1 = time.perf_counter()
            self._phase_add("prep", t1 - t0)
            packed_dev = jnp.asarray(packed)
            self._phase_add("h2d", time.perf_counter() - t1)

        pen_kw = self._decode_pen_kwargs(penalties, b, c_pad, b_actual)
        guided_kw, guided_shapes = self._decode_guided_kwargs(guided)
        bias_kw, bias_cap = self._decode_bias_kwargs(
            logit_bias, b, b_actual
        )
        cache_key = (s_pad, t_pad, pc_pad, b, c_pad, steps,
                     penalties is not None, want_logprobs, chained,
                     guided_shapes, bias_cap, stop_cap)
        if cache_key not in self._ragged_fns:
            logger.info(
                "compiling ragged round s=%d t=%d pctx=%d b=%d ctx=%d "
                "k=%d pen=%s lp=%s chained=%s guided=%s bias=%d stop=%s",
                s_pad, t_pad, pc_pad, b, c_pad, steps,
                penalties is not None, want_logprobs, chained,
                guided_shapes, bias_cap, stop_cap,
            )
            self._note_compile("ragged")
            self._ragged_fns[cache_key] = self._build_ragged(
                s_pad, t_pad, pc_pad, b, c_pad, steps,
                use_penalties=penalties is not None,
                want_logprobs=want_logprobs, chained=chained,
                guided_shapes=guided_shapes, bias_cap=bias_cap,
                stop_cap=stop_cap,
            )
        fn = self._ragged_fns[cache_key]
        lora_kw = {}
        if self.lora_manager is not None:
            slots = np.zeros((b,), dtype=np.int32)
            if lora_slots is not None:
                slots[:b_actual] = lora_slots
            pf_kw = self._packed_lora_kwargs(
                pf_lora_slots, len(pf_chunks), s_pad, t_pad
            )
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.asarray(slots),
                "pf_lora_slots": pf_kw["lora_slots"],
            }
        chained_kw = {"chained_tokens": token_ids} if chained else {}
        t2 = time.perf_counter()
        pf_sampled, pf_logits, ys, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            packed_dev,
            **chained_kw,
            **guided_kw,
            **pen_kw,
            **bias_kw,
            **lora_kw,
        )
        self._phase_add("dispatch", time.perf_counter() - t2)
        return pf_sampled, pf_logits, ys

    # stackcheck: hot-path — the single-kernel lane-typed round: ONE
    # dispatch serves prefill chunks + decode steps; fetches stay
    # deferred to the caller
    def _ragged_rows_dispatch(
        self,
        pf_chunks, pf_start_positions, pf_block_tables, pf_total_lens,
        token_ids, positions, block_tables, context_lens, steps,
        temps, top_ps, top_ks, keys, min_ps=None, pf_sampling=None,
        pf_lora_slots=None, lora_slots=None, penalties=None,
        want_logprobs=False, guided=None, logit_bias=None,
        staged=None, stop=None, pf_budgets=None, dec_budgets=None,
    ) -> tuple:
        """Kernel-mode body of ragged_dispatch (same contract): the
        program keys on the padded ROW bucket + ctx buckets —
        (r_pad, pc_pad, b, c_pad, k) — so every lane mix that packs to
        the same row bucket shares one program, and the per-layer
        attention of the whole mix is one kernel launch."""
        b = self.config.max_num_seqs
        chained = isinstance(token_ids, jax.Array)
        b_actual = len(positions)
        c_pad = self._ctx_bucket(max(context_lens) + steps - 1)
        r_pad, pc_pad = self._rows_dims(pf_chunks, pf_total_lens)
        guided_lanes = None
        if guided is not None:
            guided_lanes = (guided[1], guided[2])
        stop_cap = None
        if stop is not None:
            stop_cap = 0 if stop[3] is None else int(stop[3].shape[1])
        packed_dev = None
        if (staged is not None and chained and guided is None
                and staged[0] == ("rows", r_pad, pc_pad, c_pad)):
            # same stale-stage contract as the composed path: the
            # bucket key AND the total layout length must match, else
            # the dispatch rebuilds serially (a counted staging miss)
            want_total = sum(self._ragged_rows_pack_sizes(
                r_pad, pc_pad, b, c_pad, chained,
                guided=False, stop_cap=stop_cap,
            ))
            if int(staged[1].shape[0]) == want_total:
                packed_dev = staged[1]
        if packed_dev is None:
            t0 = time.perf_counter()
            _r, _pc, packed = self._fill_ragged_rows_pack(
                pf_chunks, pf_start_positions, pf_block_tables,
                pf_total_lens, pf_sampling, c_pad, chained, token_ids,
                positions, block_tables, context_lens, steps, temps,
                top_ps, top_ks, keys, min_ps=min_ps,
                guided_lanes=guided_lanes, stop=stop,
                pf_budgets=pf_budgets, dec_budgets=dec_budgets,
            )
            t1 = time.perf_counter()
            self._phase_add("prep", t1 - t0)
            packed_dev = jnp.asarray(packed)
            self._phase_add("h2d", time.perf_counter() - t1)

        pen_kw = self._decode_pen_kwargs(penalties, b, c_pad, b_actual)
        guided_kw, guided_shapes = self._decode_guided_kwargs(guided)
        bias_kw, bias_cap = self._decode_bias_kwargs(
            logit_bias, b, b_actual
        )
        cache_key = ("rows", r_pad, pc_pad, b, c_pad, steps,
                     penalties is not None, want_logprobs, chained,
                     guided_shapes, bias_cap, stop_cap)
        if cache_key not in self._ragged_fns:
            logger.info(
                "compiling ragged-rows round rows=%d pctx=%d b=%d "
                "ctx=%d k=%d pen=%s lp=%s chained=%s guided=%s "
                "bias=%d stop=%s",
                r_pad, pc_pad, b, c_pad, steps, penalties is not None,
                want_logprobs, chained, guided_shapes, bias_cap,
                stop_cap,
            )
            self._note_compile("ragged_rows")
            self._ragged_fns[cache_key] = self._build_ragged_rows(
                r_pad, pc_pad, b, c_pad, steps,
                use_penalties=penalties is not None,
                want_logprobs=want_logprobs, chained=chained,
                guided_shapes=guided_shapes, bias_cap=bias_cap,
                stop_cap=stop_cap,
            )
        fn = self._ragged_fns[cache_key]
        lora_kw = {}
        if self.lora_manager is not None:
            slots = np.zeros((b,), dtype=np.int32)
            if lora_slots is not None:
                slots[:b_actual] = lora_slots
            # the fused step-0 forward concatenates prefill + decode
            # slot vectors, so the prefill side always ships per-row
            pf_rows = self._rows_slot_vector(
                pf_chunks, pf_lora_slots, r_pad
            )
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.asarray(slots),
                "pf_lora_slots": jnp.asarray(pf_rows),
            }
        chained_kw = {"chained_tokens": token_ids} if chained else {}
        t2 = time.perf_counter()
        pf_sampled, pf_logits, ys, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            packed_dev,
            **chained_kw,
            **guided_kw,
            **pen_kw,
            **bias_kw,
            **lora_kw,
        )
        self._phase_add("dispatch", time.perf_counter() - t2)
        return pf_sampled, pf_logits, ys

    def precompile_ragged(
        self, context_lens: list[int], ks: list[int], max_groups: int,
        chunk_len: int, stop: bool = False, chained: bool = False,
    ) -> int:
        """Warm the ragged round's program variants: every pow2
        prefill-lane group size up to max_groups x each fused-K bucket x
        each ctx bucket, prefill lanes' context matched to the decode
        bucket (the steady-state mixed-round shape: sessions in one
        workload share a length regime). Under the single kernel the
        program keys on padded ROW-count buckets, so group sizes that
        pack to the same row bucket dedupe to ONE warm dispatch — the
        variant space shrinks from the (group, chunk) lane-mix grid to
        the row diagonal. Trash tables at the top of the pool, same
        safety contract as precompile_prefill/decode. `chained=True`
        additionally warms the staged-prefetch variant (device-array
        decode tokens — a distinct program key)."""
        b = self.config.max_num_seqs
        bs = self.block_size
        nb = self.num_blocks
        temps = np.zeros((b,), np.float32)
        top_ps = np.ones((b,), np.float32)
        top_ks = np.full((b,), -1, np.int32)
        keys = np.zeros((b, 2), np.uint32)
        groups: list[int] = []
        s = 1
        while s <= max(1, max_groups):
            groups.append(s)
            s *= 2
        seen: set[tuple] = set()
        n = 0
        for cl in context_lens:
            for k in ks:
                c_pad = self._ctx_bucket(cl + max(0, k - 1))
                ctx = c_pad - max(0, k - 1)
                clen = min(chunk_len, c_pad)
                for s in groups:
                    if self.ragged_kernel:
                        # single-kernel mode: the program keys on the
                        # padded ROW bucket, so distinct lane mixes
                        # that pack to the same row count are ONE
                        # variant — the (group, chunk) grid collapses
                        key = (
                            self._rows_bucket(s * _ceil_tq(clen)),
                            c_pad, k,
                        )
                    else:
                        key = (s, self._prefill_bucket(clen), c_pad, k)
                    if key in seen:
                        continue
                    seen.add(key)
                    npages = c_pad // bs
                    if nb < 2 * (s + 1) * npages + 64:
                        logger.warning(
                            "ragged precompile: skipping s=%d ctx=%d "
                            "k=%d — pool of %d blocks too small",
                            s, c_pad, k, nb,
                        )
                        continue
                    # decode lanes share the topmost trash table;
                    # prefill lanes stack below it, all above live KV
                    dec_table = list(range(nb - npages, nb))
                    pf_tabs = [
                        list(range(nb - (i + 2) * npages,
                                   nb - (i + 1) * npages))
                        for i in range(s)
                    ]
                    stop_kw = {}
                    if stop:
                        # budget == k: nothing freezes, full trip — the
                        # PROGRAM equals what live batches select
                        stop_kw = {"stop": (
                            np.full((b,), -1, np.int32),
                            np.zeros((b,), np.int32),
                            np.full((b,), k, np.int32),
                            None,
                        )}
                    out = self.ragged_dispatch(
                        [[1] * clen] * s, [c_pad - clen] * s, pf_tabs,
                        [c_pad] * s,
                        [1] * b, [ctx - 1] * b, [dec_table] * b,
                        [ctx] * b, k,
                        temps, top_ps, top_ks, keys, **stop_kw,
                    )
                    jax.block_until_ready(out)
                    n += 1
                    if chained and k > 1:
                        ys = out[2]
                        toks = ys[0] if isinstance(ys, tuple) else ys
                        out2 = self.ragged_dispatch(
                            [[1] * clen] * s, [c_pad - clen] * s,
                            pf_tabs, [c_pad] * s,
                            toks[-1], [ctx - 1] * b, [dec_table] * b,
                            [ctx] * b, k,
                            temps, top_ps, top_ks, keys, **stop_kw,
                        )
                        jax.block_until_ready(out2)
                        n += 1
        return n

    # -- embeddings (stateless, /v1/embeddings) ----------------------------
    def _build_embed(self, t_pad: int, c_pad: int):
        """One chunked-prefill embed step over a caller-owned scratch KV
        cache; returns (hidden-sum over valid chunk rows, kc, vc). Reuses
        llama.forward (LoRA/bias/rope can never diverge from serving) with
        the chunk x context score shape of the serving prefill path, so
        long inputs never materialize t x t attention."""
        mc = self.model_config
        scale = self._scale

        def step(params, kc, vc, toks, positions, total_len, valid_len,
                 lora=None, lora_slots=None):
            def attn(q, l, kcache, vcache):
                return xla_attn.context_attention_prefill(
                    q, kcache[l].swapaxes(0, 1), vcache[l].swapaxes(0, 1),
                    positions, total_len, scale,
                    window=self.model_config.sliding_window,
                )

            # scratch cache row == absolute position; padded chunk rows
            # carry position c_pad, landing in the extra trash row.
            # self._forward so pipeline-parallel engines stage this too
            # (a plain scan over pp-sharded params would make GSPMD
            # all-gather the full layer stack per device)
            h, kc, vc = self._forward(
                mc, params, toks, positions, kc, vc,
                write_slots=positions,
                attn_fn=attn,
                logits_rows=jnp.arange(t_pad),
                lora=lora, lora_slots=lora_slots,
                return_hidden=True,
            )  # (t_pad, hidden) f32
            keep = (positions < valid_len)[:, None].astype(jnp.float32)
            return jnp.sum(h * keep, axis=0), kc, vc

        return jax.jit(step, donate_argnums=(1, 2), **self._step_jit_kwargs())

    def embed(self, token_ids: list[int], lora_slot: int = 0) -> np.ndarray:
        """Mean-pooled + L2-normalised final hidden state -> (hidden,) f32
        (decoder-as-embedder, e5-mistral pattern). Inputs above
        max_model_len are rejected, never silently truncated."""
        t = len(token_ids)
        if t > self.max_model_len:
            raise ValueError(
                f"embedding input has {t} tokens, exceeds max_model_len="
                f"{self.max_model_len}"
            )
        mc = self.model_config
        c_pad = self._ctx_bucket(t)
        chunk = self.config.max_prefill_chunk
        # c_pad + 1 rows: the last row is the trash slot padded chunk rows
        # write into (they carry position c_pad)
        kc = jnp.zeros(
            (mc.num_layers, mc.num_kv_heads, c_pad + 1, mc.head_dim),
            self.cache_dtype,
        )
        vc = jnp.zeros_like(kc)
        lora_kw = {}
        if self.lora_manager is not None:
            lora_kw = {
                "lora": self.lora_manager.buffers,
                "lora_slots": jnp.int32(lora_slot),
            }
        pooled_sum = np.zeros((mc.hidden_size,), np.float64)
        for start in range(0, t, chunk):
            ids = token_ids[start: start + chunk]
            t_pad = self._prefill_bucket(len(ids))
            toks = np.zeros((t_pad,), np.int32)
            toks[: len(ids)] = ids
            # padded rows park at position c_pad (write redirected to 0,
            # masked out of both attention and pooling)
            positions = np.full((t_pad,), c_pad, np.int32)
            positions[: len(ids)] = np.arange(start, start + len(ids))
            key = (t_pad, c_pad)
            if key not in self._embed_fns:
                logger.info("compiling embed step t=%d ctx=%d", t_pad,
                            c_pad)
                self._note_compile("embed")
                self._embed_fns[key] = self._build_embed(t_pad, c_pad)
            part, kc, vc = self._embed_fns[key](
                self.params, kc, vc, jnp.asarray(toks),
                jnp.asarray(positions),
                jnp.int32(start + len(ids)), jnp.int32(t), **lora_kw,
            )
            pooled_sum += np.asarray(part, np.float64)
        pooled = pooled_sum / max(t, 1)
        norm = float(np.linalg.norm(pooled))
        return (pooled / max(norm, 1e-12)).astype(np.float32)

    # -- cache import/export (KV offload + PD transfer tiers) -------------
    # stackcheck: hot-path — the deferred-export snapshot is enqueued on
    # the engine step thread right after (or between) device dispatches:
    # it may only ENQUEUE the gather; the blocking d2h materialization
    # belongs to the offload worker (materialize_export)
    def stage_export_blocks(self, block_ids: list[int]) -> tuple:
        """Enqueue the device-side snapshot of whole KV blocks.

        Returns a handle of on-device arrays. Because device ops execute
        in enqueue order, any LATER dispatch that overwrites these slots
        cannot corrupt the snapshot — the caller may release the blocks
        for reuse the moment this returns."""
        idx = jnp.asarray(
            xla_attn.block_table_slots(
                jnp.asarray(block_ids, jnp.int32), self.block_size
            )
        )
        # (L, nkv, n*bs, d) gathers; async dispatch, no host sync
        return (len(block_ids), self.k_cache[:, :, idx],
                self.v_cache[:, :, idx])

    def materialize_export(self, handle: tuple) -> np.ndarray:
        """Blocking half of the deferred export (runs on the offload
        worker thread): fetch the staged gathers and relayout to the
        wire format (2, num_layers, n, nkv, block_size, d) — block count
        stays at dim 2, so offload/transfer consumers that slice or
        count blocks (`data[:, :, i]`, `data.shape[2]`) are
        layout-agnostic."""
        n, k, v = handle
        mc = self.model_config
        shape = (mc.num_layers, mc.num_kv_heads, n, self.block_size,
                 mc.head_dim)
        return np.stack([
            np.asarray(k).reshape(shape).swapaxes(1, 2),
            np.asarray(v).reshape(shape).swapaxes(1, 2),
        ])

    def export_blocks(self, block_ids: list[int]) -> np.ndarray:
        """Synchronous device->host copy of whole KV blocks (PD transfer
        server + --sync-kv-offload path)."""
        return self.materialize_export(self.stage_export_blocks(block_ids))

    def _build_import(self, n_src_pad: int, n_dst_pad: int):
        """Donated in-place scatter of staged wire-format blocks into
        the KV caches: replaces the whole-cache-reallocating eager
        `.at[].set` (which copied both cache arrays per restore)."""
        mc = self.model_config
        bs = self.block_size

        def step(kc, vc, bids, cols, staged):
            kc, vc = self._pin_cache_layout(kc, vc)
            # staged: (2, L, n_src_pad, nkv, bs, d) wire layout
            sel = staged[:, :, cols]  # (2, L, n_dst_pad, nkv, bs, d)
            hm = jnp.swapaxes(sel, 2, 3)  # head-major
            flat = hm.reshape(
                2, mc.num_layers, mc.num_kv_heads, n_dst_pad * bs,
                mc.head_dim,
            ).astype(self.cache_dtype)
            idx = xla_attn.block_table_slots(bids, bs)
            kc = kc.at[:, :, idx].set(flat[0])
            vc = vc.at[:, :, idx].set(flat[1])
            return kc, vc

        return jax.jit(step, donate_argnums=(0, 1),
                       **self._step_jit_kwargs(0))

    def _import_args(
        self, block_ids: list[int], src_cols: list[int], n_pad: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Host args for the donated scatter, padded to `n_pad`.
        Padding rows target the null block (their writes are trash by
        design) and read staged column 0 (always present)."""
        n = len(block_ids)
        bids = np.zeros((n_pad,), np.int32)
        bids[:n] = block_ids
        cols = np.zeros((n_pad,), np.int32)
        cols[:n] = src_cols
        return bids, cols

    # stackcheck: hot-path — restore staging: pad + START the h2d
    # upload; enqueue-only (no device fetch, no tier IO)
    def stage_import_blocks(self, data: np.ndarray) -> tuple:
        """Begin the restore's host->device upload. `data` is the wire
        layout (2, L, n, nkv, bs, d); the block axis pads to pow2 so the
        donated scatter compiles one variant per bucket. Returns a
        handle for import_staged_blocks. Under a mesh the handle stays
        host-side (a committed single-device put would be resharded —
        same rule as the decode/prefill staging)."""
        n = data.shape[2]
        n_pad = next_pow2(max(n, 1))
        if n_pad != n:
            pad = np.zeros(
                data.shape[:2] + (n_pad - n,) + data.shape[3:],
                dtype=data.dtype,
            )
            data = np.concatenate([data, pad], axis=2)
        if self.mesh is not None:
            return (n, data)
        return (n, jax.device_put(data))

    # stackcheck: hot-path — the restore's device-side write on the
    # admission path: one donated-jit dispatch, no host sync
    def import_staged_blocks(
        self, block_ids: list[int], handle: tuple, src_cols: list[int],
    ) -> None:
        """In-place donated scatter of staged (already uploaded/
        uploading) blocks into the KV cache. `src_cols[i]` names the
        staged block-axis column holding block_ids[i]'s contents."""
        if not block_ids:
            return
        _, staged = handle
        # pad the DST list to the staged width: partial adoptions (full
        # HBM, broken chain) reuse the SAME compiled (n, n) variant as
        # the full restore instead of compiling an off-diagonal shape
        # inside a live admission — precompile_kv_import's diagonal is
        # then the complete variant space
        n_pad = staged.shape[2]  # already pow2 (stage_import_blocks)
        bids, cols = self._import_args(block_ids, src_cols, n_pad)
        key = (n_pad, n_pad)
        fn = self._import_fns.get(key)
        if fn is None:
            logger.info("compiling kv import n_src=%d n_dst=%d", *key)
            self._note_compile("kv_import")
            fn = self._import_fns[key] = self._build_import(*key)
        self.k_cache, self.v_cache = fn(
            self.k_cache, self.v_cache, jnp.asarray(bids),
            jnp.asarray(cols), staged,
        )

    def precompile_kv_import(self, max_blocks: int) -> int:
        """Warm the donated import scatter's (n, n) pow2 diagonal up to
        max_blocks so no XLA compile lands inside a live restore. The
        diagonal IS the complete variant space: import_staged_blocks
        pads the dst list to the staged width, so partial adoptions
        never dispatch an off-diagonal shape. Writes target the null
        block (trash by design). Returns dispatches."""
        mc = self.model_config
        # the wire dtype is whatever materialize_export's np.asarray
        # yields for the cache dtype (ml_dtypes bf16 on bf16 caches) —
        # warming float32 would compile a variant live traffic never hits
        wire_dt = np.asarray(jnp.zeros((), self.cache_dtype)).dtype
        n = 0
        p = 1
        while p <= next_pow2(max(1, max_blocks)):
            data = np.zeros(
                (2, mc.num_layers, p, mc.num_kv_heads, self.block_size,
                 mc.head_dim), wire_dt,
            )
            handle = self.stage_import_blocks(data)
            self.import_staged_blocks([0] * p, handle, list(range(p)))
            n += 1
            p *= 2
        return n

    def import_blocks(self, block_ids: list[int], data: np.ndarray) -> None:
        """Host->device restore of whole KV blocks (inverse of export).
        Routed through the staged in-place scatter — a donated update
        instead of a whole-cache-reallocating eager `.at[].set`."""
        handle = self.stage_import_blocks(np.asarray(data))
        self.import_staged_blocks(
            block_ids, handle, list(range(len(block_ids)))
        )

    # -- long-context ring prefill (engine/long_prefill.py) ----------------
    def build_long_prefiller(self):
        """Construct the ("tp", "sp") ring prefiller for the long-
        prefill lane: tp matches the serving tensor-parallel size, sp =
        EngineConfig.context_parallel_size. The ring mesh prefers
        devices PAST the serving one(s) when the host has spares, so
        ring compute does not queue behind decode dispatches on the
        serving chip; with exactly tp*sp devices it shares them. The
        prefiller holds its own (re-placed) copy of the weights — the
        memory price of running two meshes, stated in tutorial 18.
        Raises when the host lacks tp*sp devices (the engine then
        serves long prompts on the chunked path)."""
        from production_stack_tpu.parallel.long_context import (
            LongContextPrefiller,
            make_sp_mesh,
        )

        cfg = self.config
        sp = cfg.context_parallel_size
        tp = max(1, cfg.tensor_parallel_size)
        if sp <= 1:
            raise ValueError("context_parallel_size must be > 1")
        devs = jax.devices()
        need = tp * sp
        serving = self.mesh.size if self.mesh is not None else 1
        if len(devs) >= serving + need:
            pool = devs[serving: serving + need]
        elif len(devs) >= need:
            pool = devs[:need]
        else:
            raise ValueError(
                f"context_parallel_size={sp} x tp={tp} needs {need} "
                f"devices; host has {len(devs)}"
            )
        mesh = make_sp_mesh(tp, sp, devices=pool)
        return LongContextPrefiller(
            self.model_config, self.params, mesh,
            cache_dtype=self.cache_dtype,
        )
