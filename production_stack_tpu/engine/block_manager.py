"""Paged KV block manager with hash-based prefix caching.

TPU-native equivalent of the KV-block bookkeeping the reference stack gets
from vLLM + LMCache (the router scrapes its effects as
`vllm:gpu_cache_usage_perc` / `vllm:gpu_prefix_cache_hit_rate`, reference:
src/vllm_router/stats/engine_stats.py:63-76). Pure host-side Python: the
device only ever sees flat slot indices, so this logic never enters jit.

Prefix caching: a *full* block of block_size tokens is content-addressed by
the chain hash of all tokens up to and including that block. Blocks with
ref_count 0 stay in an LRU "evictable" pool and can be resurrected on a hash
hit (same design as vLLM's prefix caching / LMCache's local backend).

Block 0 is reserved as the null/trash block: padded batch lanes write their
garbage K/V there, so it is never handed to a sequence.
"""

from __future__ import annotations

from collections import OrderedDict

import xxhash

NULL_BLOCK = 0


def hash_block(prev_hash: int, token_ids: tuple[int, ...],
               extra: tuple = ()) -> int:
    """Chain hash for a full block given the previous block's hash."""
    h = xxhash.xxh64()
    h.update(prev_hash.to_bytes(8, "little", signed=False))
    for t in token_ids:
        h.update(int(t).to_bytes(4, "little", signed=False))
    for e in extra:
        h.update(str(e).encode())
    return h.intdigest()


def iter_chain_hashes(token_ids, block_size: int, seed: int = 0):
    """Chain hashes for each *full* block of token_ids, lazily.

    THE one token->block-hash folding, shared by the BlockManager, the
    KV controller's prefix matcher, and the router's shared-cache
    lookup hints — every copy of this loop that drifts (seed, chunk
    boundary, partial-block handling) makes cross-component prefix
    matches miss silently, so there is exactly one. Lazy so matchers
    can stop hashing at the first miss."""
    prev = seed
    for i in range(len(token_ids) // block_size):
        prev = hash_block(
            prev, tuple(token_ids[i * block_size:(i + 1) * block_size])
        )
        yield prev


class Block:
    __slots__ = ("block_id", "ref_count", "block_hash")

    def __init__(self, block_id: int):
        self.block_id = block_id
        self.ref_count = 0
        self.block_hash: int | None = None


class BlockManager:
    """Allocator for a fixed pool of KV blocks, with prefix caching."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the null block)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching

        self.blocks = [Block(i) for i in range(num_blocks)]
        # bumped on every free(): see the note there
        self.free_epoch = 0
        # block 0 reserved as null/trash
        self.free_blocks: list[int] = list(range(num_blocks - 1, 0, -1))
        # hash -> block_id for cached full blocks (ref>=0)
        self.cached_blocks: dict[int, int] = {}
        # block_id -> None, LRU order, for ref_count==0 cached blocks
        self.evictable: OrderedDict[int, None] = OrderedDict()

        # token-level prefix-cache counters (engine /metrics contract)
        self.prefix_queries = 0
        self.prefix_hits = 0

        # KV offload hooks (wired by LLMEngine when offload is configured):
        # on_admit(hashes)      -> new cached blocks live in HBM
        # on_evict(hashes)      -> cached blocks dropped from HBM
        # on_freed_cached(pairs)-> [(block_id, hash)] just became evictable;
        #                          contents still intact, safe to d2h-export
        self.on_admit = None
        self.on_evict = None
        self.on_freed_cached = None
        # deferred-export pins: freed-but-cached blocks whose device-side
        # snapshot has not been enqueued yet (see pin_for_export)
        self._export_pins: set[int] = set()

    # -- capacity ---------------------------------------------------------
    @property
    def num_free_blocks(self) -> int:
        return len(self.free_blocks) + len(self.evictable)

    @property
    def usage(self) -> float:
        """Fraction of blocks actively referenced (the vllm:gpu_cache_usage_perc)."""
        usable = self.num_blocks - 1
        return (usable - self.num_free_blocks) / max(1, usable)

    def can_allocate(self, num_new_blocks: int) -> bool:
        return self.num_free_blocks >= num_new_blocks

    # -- low-level alloc --------------------------------------------------
    def _pop_free_block(self) -> int:
        if self.free_blocks:
            return self.free_blocks.pop()
        if self.evictable:
            bid, _ = self.evictable.popitem(last=False)  # LRU
            blk = self.blocks[bid]
            if blk.block_hash is not None:
                self.cached_blocks.pop(blk.block_hash, None)
                if self.on_evict is not None:
                    self.on_evict([blk.block_hash])
                blk.block_hash = None
            return bid
        raise RuntimeError("out of KV blocks")

    def _take(self, bid: int) -> None:
        blk = self.blocks[bid]
        if blk.ref_count == 0 and bid in self.evictable:
            del self.evictable[bid]
        blk.ref_count += 1

    # -- sequence-level API ----------------------------------------------
    def block_hashes_for(self, token_ids: list[int],
                         seed: int = 0) -> list[int]:
        """Chain hashes for each *full* block of token_ids.

        `seed` starts the chain (0 = base model; LoRA requests pass a
        per-adapter seed so adapters never share KV blocks)."""
        return list(
            iter_chain_hashes(token_ids, self.block_size, seed)
        )

    def contains_hash(self, h: int) -> bool:
        return h in self.cached_blocks

    def match_prefix(self, token_ids: list[int],
                     seed: int = 0) -> tuple[list[int], int]:
        """Longest cached prefix: returns (block_ids, num_cached_tokens).

        Does NOT take references; pairs with allocate_prompt.
        """
        if not self.enable_prefix_caching:
            return [], 0
        matched: list[int] = []
        for h in self.block_hashes_for(token_ids, seed):
            bid = self.cached_blocks.get(h)
            if bid is None:
                break
            matched.append(bid)
        return matched, len(matched) * self.block_size

    def allocate_prompt(
        self, token_ids: list[int], seed: int = 0,
        reuse_cache: bool = True,
    ) -> tuple[list[int], int] | None:
        """Allocate the block table for a prompt, reusing cached prefix blocks.

        Returns (block_table, num_cached_tokens) or None if out of blocks.
        num_cached_tokens is capped at len(token_ids)-1 so at least one token
        is computed (we need its logits to start decoding).

        `reuse_cache=False` skips prefix matching (the computed blocks
        still REGISTER afterwards): prompt_logprobs needs every position
        actually computed — a cache hit would skip its rows."""
        n = len(token_ids)
        self.prefix_queries += n
        if not reuse_cache:
            matched, cached_tokens = [], 0
        else:
            matched, cached_tokens = self.match_prefix(token_ids, seed)
        cached_tokens = min(cached_tokens, n - 1)
        num_matched_blocks = cached_tokens // self.block_size
        matched = matched[:num_matched_blocks]
        # re-floor to the adopted block boundary: after the n-1 cap the
        # token count must match the blocks actually taken, otherwise a
        # fully-cached prompt whose length is a block multiple starts
        # computing at a position whose preceding KV was never adopted
        # (attention over zero blocks => corrupt logits)
        cached_tokens = num_matched_blocks * self.block_size

        total_blocks = (n + self.block_size - 1) // self.block_size
        need_new = total_blocks - len(matched)
        # matched blocks sitting in the evictable pool stop being free the
        # moment we take them, so they must not count toward need_new
        evictable_matched = sum(1 for b in matched if b in self.evictable)
        if self.num_free_blocks - evictable_matched < need_new:
            self.prefix_queries -= n  # admission failed; don't skew stats
            return None

        self.prefix_hits += cached_tokens
        table = []
        for bid in matched:
            self._take(bid)
            table.append(bid)
        for _ in range(need_new):
            bid = self._pop_free_block()
            self._take(bid)
            table.append(bid)
        return table, cached_tokens

    def ensure_capacity(
        self, num_tokens: int, block_table: list[int]
    ) -> bool:
        """Grow block_table (in place) until it covers num_tokens positions.

        Returns False if a new block was needed but none was available.
        """
        while len(block_table) * self.block_size < num_tokens:
            if self.num_free_blocks == 0:
                return False
            bid = self._pop_free_block()
            self._take(bid)
            block_table.append(bid)
        return True

    def register_block(
        self, prev_hash: int, token_ids: tuple[int, ...], block_id: int
    ) -> int:
        """Incrementally content-address one full block; returns its hash."""
        h = hash_block(prev_hash, token_ids)
        if not self.enable_prefix_caching:
            return h
        blk = self.blocks[block_id]
        if blk.block_hash is None and h not in self.cached_blocks:
            blk.block_hash = h
            self.cached_blocks[h] = block_id
            if self.on_admit is not None:
                self.on_admit([h])
        return h

    def adopt_cached_block(self, h: int) -> int | None:
        """Claim a free block to hold offload-restored contents for hash h.

        The block enters the cache ref_count==0 and evictable, exactly like
        a block left behind by a finished sequence; the caller must import
        the KV contents before the next model step. Returns None when no
        block can be claimed (restore is best-effort, admission continues
        with whatever prefix is already in HBM).
        """
        if not self.enable_prefix_caching or h in self.cached_blocks:
            return None
        if not self.free_blocks and not self.evictable:
            return None
        bid = self._pop_free_block()
        blk = self.blocks[bid]
        blk.block_hash = h
        self.cached_blocks[h] = bid
        self.evictable[bid] = None
        if self.on_admit is not None:
            self.on_admit([h])
        return bid

    def can_adopt_another(self, n_adopted: int) -> bool:
        """True while one more adopt_cached_block cannot cannibalize the
        caller's own freshly-adopted blocks. Adopted blocks enter the
        evictable pool (newest end), so _pop_free_block only reaches
        them once free_blocks is empty AND every OLDER evictable entry
        is consumed — i.e. when the caller's n_adopted blocks are all
        that remains. Evicting one would hand its block id out twice in
        the same restore: a donated scatter with duplicate destination
        indices has undefined write order, leaving a live cache hash
        holding another hash's KV."""
        return len(self.free_blocks) + len(self.evictable) > n_adopted

    def drop_cached_block(self, h: int) -> None:
        """Remove an UNREFERENCED cached block from the cache and return
        it to the free pool (a restore landing failed AFTER adoption —
        leaving the entry would serve never-written garbage KV to every
        later prefix hit on this hash)."""
        bid = self.cached_blocks.pop(h, None)
        if bid is None:
            return
        blk = self.blocks[bid]
        assert blk.ref_count == 0, "drop_cached_block on a live block"
        blk.block_hash = None
        if bid in self.evictable:
            del self.evictable[bid]
        self.free_blocks.append(bid)
        if self.on_evict is not None:
            self.on_evict([h])

    # -- deferred-export pinning -------------------------------------------
    def pin_for_export(self, block_ids: list[int]) -> None:
        """Take freed-but-cached blocks out of the reusable pools until
        their deferred d2h export snapshot is enqueued (unpin_exported).

        A pinned block keeps its cache entry — prefix hits may still
        re-take it (contents are immutable for a registered hash) — it
        just stops being allocatable, so no later dispatch can overwrite
        it before the export's device-side copy is ordered. Idempotent:
        re-pinning an already-pinned or re-taken block is a no-op."""
        for bid in block_ids:
            blk = self.blocks[bid]
            if blk.ref_count == 0 and bid in self.evictable:
                del self.evictable[bid]
                self._export_pins.add(bid)

    def unpin_exported(self, block_ids: list[int]) -> None:
        """The export snapshot is enqueued (device-ordered before any
        later write): return still-free pinned blocks to their pools."""
        for bid in block_ids:
            if bid not in self._export_pins:
                continue
            self._export_pins.discard(bid)
            blk = self.blocks[bid]
            if blk.ref_count == 0 and bid not in self.evictable:
                if blk.block_hash is not None:
                    self.evictable[bid] = None
                else:
                    self.free_blocks.append(bid)

    def free(self, block_table: list[int]) -> None:
        """Release a sequence's references; cached blocks become evictable."""
        # table-identity epoch: freed block ids may be handed to another
        # sequence, so anything caching a snapshot of LIVE page tables
        # (the staged h2d prefetch, llm_engine._stage_fingerprint) must
        # observe a bump and rebuild — a same-length re-allocated table
        # is indistinguishable by shape alone
        self.free_epoch += 1
        freed_cached: list[tuple[int, int]] = []
        for bid in block_table:
            blk = self.blocks[bid]
            blk.ref_count -= 1
            assert blk.ref_count >= 0, f"double free of block {bid}"
            if blk.ref_count == 0:
                if blk.block_hash is not None:
                    if bid not in self._export_pins:
                        # keep contents, LRU-evictable; a still-pinned
                        # block stays out of the pool until its export
                        # snapshot is enqueued (unpin_exported)
                        self.evictable[bid] = None
                    freed_cached.append((bid, blk.block_hash))
                else:
                    self.free_blocks.append(bid)
        if freed_cached and self.on_freed_cached is not None:
            # one batched d2h export per freed sequence (see kv/offload.py)
            self.on_freed_cached(freed_cached)
