"""Async facade over LLMEngine for the HTTP server.

The engine step loop (device dispatch) runs on a dedicated thread so the
asyncio event loop stays responsive for streaming; per-request outputs are
delivered to asyncio queues via call_soon_threadsafe. This mirrors the
process shape of the reference's engines (uvicorn front + engine core), minus
GPUs: on TPU the device work is already async (XLA dispatch returns before
compute finishes), so one runner thread saturates the chip.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections.abc import AsyncIterator

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.llm_engine import LLMEngine
from production_stack_tpu.engine.outputs import (
    EngineStatsSnapshot,
    RequestOutput,
)
from production_stack_tpu.engine.sampling_params import SamplingParams
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


class EngineSleepingError(RuntimeError):
    pass


class AsyncLLMEngine:
    def __init__(self, config: EngineConfig, params: dict | None = None):
        self.config = config
        self.engine = LLMEngine(config, params=params)
        self._loop: asyncio.AbstractEventLoop | None = None
        # the step thread's _fail_inflight iterates these under the lock;
        # loop-side writes hold it too, except the GIL-atomic single-op
        # reads/pops on hot paths (suppressed with rationale in place)
        self._streams: dict[str, asyncio.Queue] = {}  # guarded by: self._lock
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._step_loop, name="engine-step-loop", daemon=True
        )
        # sleep/wake lifecycle (reference parity: engine /sleep /wake_up,
        # reference: src/vllm_router/service_discovery.py:414-441)
        self.sleeping = False
        self.sleep_level = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._loop = loop or asyncio.get_event_loop()
        self._thread.start()

    def shutdown(self) -> None:
        self._stopped = True
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        self.engine.shutdown()

    # -- step loop thread --------------------------------------------------
    def _step_loop(self) -> None:
        logger.info("engine step loop started")
        while not self._stopped:
            if self.sleeping:
                self._wake.wait(timeout=0.1)
                self._wake.clear()
                continue
            try:
                with self._lock:
                    busy = self.engine.has_unfinished()
                    outputs = self.engine.step() if busy else []
            except Exception:  # noqa: BLE001 — a step failure must fail
                # the in-flight REQUESTS, not the serving thread: a dead
                # step loop wedges every current and future request
                logger.exception(
                    "engine step failed; aborting in-flight requests"
                )
                outputs = self._fail_inflight()
                busy = True
                # if the engine state is corrupt enough that aborts
                # also fail, has_unfinished() can stay true forever —
                # backoff bounds the retry/log rate instead of pegging
                # the thread in a no-sleep exception loop
                # audited for stackcheck's blocking-async rule: _step_loop
                # runs on the dedicated engine-step thread (self._thread),
                # never the event loop, so a blocking backoff is the
                # intent (the rule only scans async defs; no directive
                # needed — this note is the audit trail)
                time.sleep(0.5)
            if outputs and self._loop is not None:
                self._loop.call_soon_threadsafe(self._deliver, outputs)
            if not busy:
                self._wake.wait(timeout=0.02)
                self._wake.clear()

    def _fail_inflight(self) -> list[RequestOutput]:
        """Abort every engine request and emit finished error outputs so
        waiting streams terminate instead of hanging forever."""
        from production_stack_tpu.engine.sequence import RequestMetrics

        outs: list[RequestOutput] = []
        with self._lock:
            for request_id in list(self._streams):
                try:
                    self.engine.abort_request(request_id)
                except Exception:  # noqa: BLE001 — state may be corrupt
                    logger.exception("abort failed for %s", request_id)
                outs.append(RequestOutput(
                    request_id=request_id,
                    prompt_token_ids=[],
                    token_ids=[],
                    new_token_ids=[],
                    text="",
                    delta_text="",
                    finished=True,
                    finish_reason="error",
                    metrics=RequestMetrics(arrival_time=time.time()),
                ))
        return outs

    def _deliver(self, outputs: list[RequestOutput]) -> None:
        for out in outputs:
            # stackcheck: disable=guarded-by-lock — loop-thread dict.get
            # is GIL-atomic and _fail_inflight snapshots via list(); taking
            # the lock here would stall delivery behind the next
            # engine.step (the step thread holds it for the whole step)
            q = self._streams.get(out.request_id)
            if q is not None:
                q.put_nowait(out)

    # -- request API -------------------------------------------------------
    async def generate(
        self,
        request_id: str,
        prompt: str | None = None,
        prompt_token_ids: list[int] | None = None,
        sampling_params: SamplingParams | None = None,
        lora_name: str | None = None,
        priority: int = 0,
        traceparent: str | None = None,
    ) -> AsyncIterator[RequestOutput]:
        if self.sleeping:
            raise EngineSleepingError("engine is sleeping")
        q: asyncio.Queue[RequestOutput] = asyncio.Queue()
        finished = False
        try:
            with self._lock:
                self._streams[request_id] = q
                self.engine.add_request(
                    request_id,
                    prompt=prompt,
                    prompt_token_ids=prompt_token_ids,
                    sampling_params=sampling_params,
                    arrival_time=time.time(),
                    lora_name=lora_name,
                    priority=priority,
                    traceparent=traceparent,
                )
            self._wake.set()
            while True:
                out = await q.get()
                finished = out.finished
                yield out
                if finished:
                    break
        finally:
            # stackcheck: disable=guarded-by-lock — loop-thread dict.pop is
            # GIL-atomic vs _fail_inflight's list() snapshot; taking the
            # lock on every NORMAL completion would stall the event loop
            # behind the step thread's full engine.step
            self._streams.pop(request_id, None)
            if not finished:
                with self._lock:
                    self.engine.abort_request(request_id)

    async def abort(self, request_id: str) -> bool:
        with self._lock:
            return self.engine.abort_request(request_id)

    def has_request(self, request_id: str) -> bool:
        return self.engine.has_request(request_id)

    def has_request_prefix(self, request_id: str) -> bool:
        return self.engine.has_request_prefix(request_id)

    # -- introspection -----------------------------------------------------
    def stats(self) -> EngineStatsSnapshot:
        with self._lock:
            return self.engine.stats()

    def drain_kv_observations(self) -> tuple[list[float], list[float]]:
        """KV export/restore histogram observations since the last
        drain. Lock-free: the underlying deque pops are GIL-atomic vs
        the step/worker threads' appends."""
        return self.engine.drain_kv_observations()

    def drain_decode_k_observations(self) -> list[int]:
        """Chosen-K observations (tpu:decode_k) since the last drain.
        Lock-free: same GIL-atomic deque contract as the KV drain."""
        return self.engine.drain_decode_k_observations()

    def drain_ragged_observations(self) -> list[int]:
        """Ragged lane-mix observations (tpu:ragged_lane_mix) since the
        last drain. Lock-free: same GIL-atomic deque contract."""
        return self.engine.drain_ragged_observations()

    @property
    def tokenizer(self):
        return self.engine.tokenizer

    @property
    def timeline(self):
        """Per-request lifecycle recorder (tracing.TimelineRecorder)."""
        return self.engine.timeline

    @property
    def long_prefill(self):
        """Long-prefill ring manager (None = lane off) — the server's
        /v1/models card advertises sp capability from it."""
        return self.engine.long_prefill

    @property
    def tracer(self):
        """Engine-side span tracer (tracing.RequestTracer)."""
        return self.engine.tracer

    # -- sleep / wake ------------------------------------------------------
    def sleep(self, level: int = 1) -> None:
        """Pause serving. Level 1 keeps weights; level 2 is a deep sleep
        (the KV cache is dropped either way once in-flight work drains)."""
        self.sleeping = True
        self.sleep_level = level
        logger.info("engine going to sleep (level %d)", level)

    def wake_up(self) -> None:
        self.sleeping = False
        self.sleep_level = 0
        self._wake.set()
        logger.info("engine woke up")

    def is_sleeping(self) -> bool:
        return self.sleeping
