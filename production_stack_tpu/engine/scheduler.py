"""Continuous-batching scheduler with chunked prefill and preemption.

The capability contract mirrors what the reference stack's engines provide
(continuous batching + chunked prefill flags in reference:
helm/templates/deployment-vllm-multi.yaml:140-146), re-shaped for TPU/XLA:
each engine step is either ONE packed prefill dispatch (chunks from up to
max_prefill_seqs sequences, each bucketed to a static length) or ONE decode
batch (fixed lane count), so every device program has a static shape and
jit traces a handful of bucket variants total. Prefill is prefill-priority
(lowest TTFT, the benchmark's headline metric) with a token budget per
chunk; decode packs all running sequences into one batch.

Queues: waiting (FIFO admission) -> running; preemption-by-recomputation
pushes the youngest running sequence back to the front of waiting when KV
blocks run out (vLLM v0 semantics).

Prefill/decode interleaving: a long multi-chunk prefill must not starve
running decodes (the reference stack's engines mix chunked prefill with
decode in one step — reference: helm/templates/deployment-vllm-multi.yaml:140-146;
our static-shape design alternates instead). `decode_interleave = K` caps
consecutive prefill DISPATCHES at K while any decode-ready sequence exists
(a packed dispatch of up to max_prefill_seqs chunks spends ONE unit of
that budget — through a remote chip the dispatch RTT, not the chunk
count, dominates its wall cost), so the inter-token gap of a running
stream is bounded by ~K prefill dispatches + one decode step regardless
of how many new users are admitted.

Unified ragged dispatch (`ragged_dispatch=True`): the alternation above
disappears entirely. `plan_ragged_round` packs every mid-prefill
runner's next chunk AND the decode-ready batch into ONE lane-typed
round (the engine dispatches both halves in a single device program —
model_runner.ragged_dispatch), so a waiting prefill claims a lane in
the very next round instead of queueing behind the interleave streak,
and the admission-K clamp no longer applies to in-round prefill work
(pick_decode_k's ragged branch). The streak counter, staged-bypass
accounting, and clamp stay in place for the split path
(`--no-ragged-dispatch`, multihost, async-chained rounds).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from production_stack_tpu.engine.block_manager import BlockManager
from production_stack_tpu.engine.sequence import Sequence, SequenceStatus
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


@dataclass
class PrefillWork:
    seq: Sequence
    chunk_start: int  # == seq.num_computed_tokens at schedule time
    chunk_len: int

    @property
    def is_last_chunk(self) -> bool:
        return (
            self.chunk_start + self.chunk_len >= self.seq.num_prompt_tokens
        )


@dataclass
class DecodeWork:
    seqs: list[Sequence]
    # fused decode iterations this round (elastic fused decode): the
    # scheduler sizes each round from pow2 buckets up to decode_k_cap —
    # clamped low under admission pressure, bounded by the batch's
    # remaining-token budget; the cap itself with adaptive K off
    k: int = 1


@dataclass
class SchedulerOutput:
    # one step runs EVERY listed prefill chunk in a single packed
    # dispatch (cross-sequence prefill packing); empty list = no prefill
    prefills: list[PrefillWork] = field(default_factory=list)
    decode: DecodeWork | None = None
    preempted: list[Sequence] = field(default_factory=list)
    # sequences rejected at admission (e.g. prompt too long); the engine
    # must emit a final aborted output for these so clients don't hang
    aborted: list[Sequence] = field(default_factory=list)

    @property
    def prefill(self) -> PrefillWork | None:
        """First scheduled prefill chunk (single-chunk-era accessor)."""
        return self.prefills[0] if self.prefills else None

    @property
    def is_empty(self) -> bool:
        return (
            not self.prefills
            and self.decode is None
            and not self.aborted
        )

    @property
    def is_ragged(self) -> bool:
        """True for a lane-typed mixed round (unified ragged dispatch):
        prefill-chunk lanes AND a decode batch planned together. The
        split path never produces one — prefills and decode are mutually
        exclusive there."""
        return bool(self.prefills) and self.decode is not None


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 8
    max_prefill_chunk: int = 512
    max_model_len: int = 8192
    enable_chunked_prefill: bool = True
    # cross-sequence prefill packing: chunks from up to this many
    # sequences share one dispatch. Packing needs chunked prefill (each
    # chunk is bounded by max_prefill_chunk, so a packed program is at
    # most max_prefill_seqs x max_prefill_chunk tokens); with chunking
    # off, groups stay at 1.
    max_prefill_seqs: int = 8
    # "fcfs" | "priority" (see EngineConfig.scheduling_policy)
    scheduling_policy: str = "fcfs"
    # max consecutive prefill dispatches (each packing up to
    # max_prefill_seqs chunks) while decode-ready sequences wait;
    # 0 disables interleaving (prefill runs to completion first)
    decode_interleave: int = 1
    # extra decode positions to reserve per scheduled sequence so a
    # multi-step dispatch (num_scheduler_steps - 1 lookahead) never runs
    # off the end of its block table mid-scan (always the CAP, so a
    # round sized below the cap is trivially covered)
    decode_lookahead: int = 0
    # fused decode iterations per dispatch, ceiling (engine
    # num_scheduler_steps); pick_decode_k sizes each round up to it
    decode_k_cap: int = 1
    # admission-aware adaptive K (EngineConfig.adaptive_decode_k):
    # False = every round dispatches the full cap
    adaptive_decode_k: bool = False
    # pipelined prefill: a chunk whose packed h2d buffer is already
    # uploaded (engine sets `staged_prefill_ready`) is admitted as
    # zero cost against the decode interleave — cold multi-chunk
    # prefills then drain in consecutive rounds instead of one chunk
    # per decode round. This caps how many consecutive staged
    # dispatches may bypass starvation before decode gets its turn
    # (bounds worst-case ITL for very long prompts).
    max_staged_prefill_run: int = 8
    # unified ragged dispatch (EngineConfig.ragged_dispatch, gated by
    # the engine for multihost/async/mesh): plan ONE lane-typed round
    # carrying prefill-chunk lanes AND the decode batch together —
    # dissolves the interleave streak and the admission-K clamp for
    # in-round prefill work (plan_ragged_round / pick_decode_k)
    ragged_dispatch: bool = False
    # long-prefill lane (EngineConfig.long_prefill_threshold, set by
    # the engine only when its ring manager actually built): an
    # admitted prompt whose uncached remainder exceeds this many
    # tokens is handed to the `long_prefill` hook instead of the
    # chunked lanes — the engine drives its ring chunks and KV landing
    # itself, one enqueue per step, so decode/ragged rounds for other
    # users keep running. 0 = off.
    long_prefill_threshold: int = 0


def decode_k_buckets(cap: int, adaptive: bool) -> list[int]:
    """The fused-decode K program variants a serving config can
    dispatch: just the cap with adaptive K off, plus every pow2 below
    it with adaptive K on (pick_decode_k rounds remaining budgets UP
    to the next pow2, so these are exactly the reachable Ks). The ONE
    copy shared by LLMEngine.precompile_serving and bench.py's warmup
    so the warmed variant set can never drift from the scheduler's
    rounding."""
    cap = max(1, cap)
    ks = {cap}
    if adaptive and cap > 1:
        p = 1
        while p < cap:
            ks.add(p)
            p *= 2
    return sorted(ks)


def decode_precompile_variants(
    cap: int, adaptive: bool, *,
    overlap: bool, async_chained: bool, device_stop: bool,
) -> list[tuple[int, bool, bool]]:
    """(k, chained, stop) decode program variants a serving config
    dispatches — the ONE copy of the variant-selection policy shared by
    LLMEngine.precompile_serving and bench.py's warmup, so neither can
    silently warm a different set than the runtime selects (a missed
    variant = a mid-request XLA compile). `overlap` = async decode OR
    h2d prefetch (both dispatch the chained program); `async_chained`
    rounds never carry stop masks (the chain commits round N+1 before
    round N's valid counts exist), so async engines warm fixed-trip
    programs instead."""
    return [
        (
            k,
            overlap and k > 1,
            device_stop and not async_chained and k > 1,
        )
        for k in decode_k_buckets(cap, adaptive)
    ]


class Scheduler:
    def __init__(self, config: SchedulerConfig, block_manager: BlockManager):
        self.config = config
        self.block_manager = block_manager
        self.waiting: deque[Sequence] = deque()
        self.running: list[Sequence] = []
        # optional hook (LLMEngine._restore_from_offload): pull offloaded
        # KV blocks back into HBM before prompt allocation. Returns
        # truthy to proceed with admission; falsy to DEFER this request
        # (its staged restore — tier fetch + h2d upload — is still in
        # flight; admission order is preserved, so the loop breaks and
        # retries next step while decode keeps running)
        self.kv_restore = None
        # optional hook (LLMEngine._flush_kv_exports): enqueue the
        # deferred-export device snapshot NOW, releasing export-pinned
        # blocks back to the pool. Returns True when anything was
        # flushed — callers retry the failed allocation once before
        # falling back to preemption. The flush is enqueue-only (the
        # snapshot is device-ordered before any later dispatch's
        # writes), so calling it mid-schedule costs no stall.
        self.kv_flush = None
        # optional hook (LLMEngine._begin_long_prefill): claim an
        # admitted sequence for the long-prefill lane (context-parallel
        # ring prefill). The hook marks seq.long_prefill_active and
        # returns truthy when it takes the sequence; a declined
        # sequence (LoRA, prompt_logprobs, ring unavailable) serves on
        # the ordinary chunked lanes. Long-lane sequences are skipped
        # by BOTH prefill planners below — the engine drives their
        # chunks outside schedule().
        self.long_prefill = None
        # optional request-lifecycle recorder (tracing.TimelineRecorder,
        # set by LLMEngine): admit/resume/preempt events for the
        # per-request timeline; None/disabled costs one check
        self.timeline = None
        self._prefill_streak = 0  # consecutive prefill steps scheduled
        # engine-maintained hint (pipelined prefill): the next prefill
        # dispatch's packed buffer is already on device, so admitting it
        # costs ~no link time; it bypasses the interleave's starvation
        # gate, bounded by max_staged_prefill_run consecutive bypasses
        self.staged_prefill_ready = False
        self._staged_run = 0

    # -- queue introspection (feeds the vllm:num_requests_* gauges) -------
    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    # -- entry points -----------------------------------------------------
    def add_seq(self, seq: Sequence) -> None:
        seq.status = SequenceStatus.WAITING
        self.waiting.append(seq)

    def abort(self, request_id: str) -> bool:
        for i, seq in enumerate(self.waiting):
            if seq.request_id == request_id:
                seq.status = SequenceStatus.FINISHED_ABORTED
                del self.waiting[i]
                return True
        for seq in list(self.running):
            if seq.request_id == request_id:
                seq.status = SequenceStatus.FINISHED_ABORTED
                self.free_finished(seq)
                return True
        return False

    def free_finished(self, seq: Sequence) -> None:
        if seq in self.running:
            self.running.remove(seq)
        self.block_manager.free(seq.block_table)
        seq.block_table = []

    # -- scheduling -------------------------------------------------------
    def schedule(self) -> SchedulerOutput:
        out = SchedulerOutput()
        restore_deferred = False

        # 1) admit waiting sequences while there is room
        while self.waiting and len(self.running) < self.config.max_num_seqs:
            if self.config.scheduling_policy == "priority":
                # lower priority value first, FIFO within a class; the
                # waiting queue is short (bounded by arrival rate), so a
                # linear scan beats maintaining a heap through the
                # deque's other uses (preemption pushes LEFT)
                seq = min(
                    self.waiting,
                    key=lambda s: (s.priority, s.arrival_ordinal),
                )
                if seq is not self.waiting[0]:
                    self.waiting.remove(seq)
                    self.waiting.appendleft(seq)
            seq = self.waiting[0]
            bm = self.block_manager
            min_blocks = (
                seq.num_prompt_tokens + 1 + bm.block_size - 1
            ) // bm.block_size
            if (
                seq.num_prompt_tokens + 1 > self.config.max_model_len
                or min_blocks > bm.num_blocks - 1
            ):
                logger.warning(
                    "request %s cannot fit (prompt %d tokens, "
                    "max_model_len %d, pool %d blocks); aborting",
                    seq.request_id, seq.num_prompt_tokens,
                    self.config.max_model_len, bm.num_blocks - 1,
                )
                seq.status = SequenceStatus.FINISHED_ABORTED
                self.waiting.popleft()
                out.aborted.append(seq)
                continue
            if self.kv_restore is not None:
                try:
                    proceed = self.kv_restore(seq)
                except Exception:  # noqa: BLE001 — restore is best-effort;
                    # a failure must never kill the step loop (the prompt
                    # is simply recomputed from scratch)
                    logger.exception("kv restore failed; recomputing prefix")
                    proceed = True
                if not proceed:
                    # staged restore in flight: hold this admission slot
                    # (FIFO preserved) and let decode run; the engine's
                    # wait budget bounds how long a wedged tier can
                    # defer (then the hook returns True = recompute)
                    restore_deferred = True
                    break
            alloc = None
            for _ in range(2):
                alloc = self.block_manager.allocate_prompt(
                    seq.prompt_token_ids, seed=seq.hash_seed,
                    # prompt_logprobs must COMPUTE every position; a
                    # prefix hit would skip its rows (vLLM disables
                    # reuse the same way for these requests)
                    reuse_cache=(
                        seq.sampling_params.prompt_logprobs is None
                    ),
                )
                if alloc is not None or self.kv_flush is None or \
                        not self.kv_flush():
                    break
                # export-pinned blocks just returned to the pool: retry
                # once before escalating to preemption
            if alloc is None:
                if self._priority_preempt_for(seq, out):
                    continue  # blocks freed; retry this admission
                break  # out of blocks; retry next step
            table, cached = alloc
            seq.block_table = table
            seq.num_computed_tokens = cached
            seq.metrics.num_cached_prompt_tokens = cached
            seq.status = SequenceStatus.RUNNING
            self.waiting.popleft()
            self.running.append(seq)
            self._note_admitted(seq)
            if (
                self.long_prefill is not None
                and self.config.long_prefill_threshold > 0
                and seq.num_uncomputed_prompt_tokens
                > self.config.long_prefill_threshold
            ):
                # long-prefill lane: the ring prefill computes this
                # prompt off the chunked path (admission still gated
                # the FULL chain's block allocation above — a prompt
                # the pool cannot hold was rejected/deferred, the
                # cluster-level gate is the router's context-window
                # filter on the /v1/models card)
                try:
                    self.long_prefill(seq)
                except Exception:  # noqa: BLE001 — the claim is
                    # best-effort: a ring failure must never kill the
                    # step loop; the chunked planners serve the prompt
                    logger.exception(
                        "long-prefill claim failed for %s; serving "
                        "via chunked prefill", seq.request_id,
                    )
        # priority policy: a waiting higher-priority request CLAIMS a
        # lane from a running lower-priority one (vLLM preempts for
        # priority, not just for block exhaustion) — without this,
        # priority would only reorder the waiting queue and inversion
        # under a full lane pool would be unbounded
        if (
            self.config.scheduling_policy == "priority"
            and self.waiting
            and not restore_deferred  # a deferral is not a capacity
            # shortage: evicting a runner for a request that cannot
            # admit yet would recompute the victim for nothing
            and len(self.running) >= self.config.max_num_seqs
        ):
            cand = min(
                self.waiting,
                key=lambda s: (s.priority, s.arrival_ordinal),
            )
            worst = max(
                self.running,
                key=lambda s: (s.priority, s.arrival_ordinal),
            )
            if (cand.priority, cand.arrival_ordinal) < (
                worst.priority, worst.arrival_ordinal
            ) and self._eviction_can_fit(cand):
                self._preempt(worst, out)
                # one lane per step keeps the preemption cost bounded;
                # the next schedule() admits cand through the normal
                # loop (and preempts again if more claims remain)
                return self.schedule_admit_retry(out)

        # unified ragged dispatch: no interleave arbitration — every
        # mid-prefill runner's next chunk AND the decode-ready batch
        # share ONE lane-typed round
        if self.config.ragged_dispatch:
            return self.plan_ragged_round(out)

        # 2) prefill priority: oldest running sequence with prompt left —
        # UNLESS decode-ready sequences have already waited through
        # `decode_interleave` consecutive prefill DISPATCHES (each one
        # packed group; bounded ITL)
        has_decode_ready = any(
            s.prefill_done and not s.finished for s in self.running
        )
        staged_bypass = (
            self.staged_prefill_ready
            and self._staged_run < self.config.max_staged_prefill_run
        )
        decode_starved = (
            self.config.decode_interleave > 0
            and has_decode_ready
            and not staged_bypass
            and self._prefill_streak >= self.config.decode_interleave
        )
        if not decode_starved:
            group_cap = (
                self.config.max_prefill_seqs
                if self.config.enable_chunked_prefill
                else 1
            )
            for seq in self.running:
                if seq.prefill_done or seq.long_prefill_active:
                    # long-lane sequences ring outside schedule(); a
                    # chunked dispatch for them would double-compute
                    continue
                if len(out.prefills) >= group_cap:
                    break
                chunk_len = seq.num_uncomputed_prompt_tokens
                if self.config.enable_chunked_prefill:
                    chunk_len = min(
                        chunk_len, self.config.max_prefill_chunk
                    )
                out.prefills.append(PrefillWork(
                    seq=seq,
                    chunk_start=seq.num_computed_tokens,
                    chunk_len=chunk_len,
                ))
            if out.prefills:
                # streak counts DISPATCHES, not chunks: a packed group of
                # N chunks is ONE device dispatch whose wall cost is
                # dominated by the dispatch itself (through a tunneled
                # chip, ~170ms RTT vs ~tens of ms marginal compute per
                # extra chunk). Counting chunks (the earlier advisor-r3
                # reading) throttled admission to ONE UNPACKED chunk per
                # decode round under load — measured on hardware as
                # round-1 p50 TTFT 15.6s in the 10-round workload while
                # packed admission holds it in the low seconds for the
                # same ITL bound.
                if (staged_bypass and has_decode_ready
                        and self._prefill_streak
                        >= self.config.decode_interleave):
                    # zero-cost admission: this dispatch's h2d already
                    # overlapped earlier compute (pipelined prefill);
                    # decode's extra wait is bounded by the staged-run
                    # cap, and a stale stage is converted back into a
                    # charged dispatch via note_staged_prefill_miss
                    self._staged_run += 1
                else:
                    self._prefill_streak += 1
                return out
        self._prefill_streak = 0
        self._staged_run = 0

        # 3) otherwise decode every decode-ready running sequence (mid-
        # prefill sequences sit out the interleaved decode steps)
        decode_seqs = self._collect_decode_ready(out)
        if decode_seqs:
            out.decode = DecodeWork(
                seqs=decode_seqs, k=self.pick_decode_k(decode_seqs)
            )
        return out

    def _collect_decode_ready(
        self, out: SchedulerOutput
    ) -> list[Sequence]:
        """Capacity-checked decode batch: every decode-ready running
        sequence whose block table can grow to cover this round's
        lookahead, preempting (or self-preempting) on exhaustion —
        shared by the split path's decode step and plan_ragged_round."""
        decode_seqs: list[Sequence] = []
        for seq in list(self.running):
            if seq.finished or seq not in self.running:
                # may have been preempted while scheduling an earlier seq
                continue
            if not seq.prefill_done:
                continue
            while not self.block_manager.ensure_capacity(
                seq.num_tokens + self.config.decode_lookahead,
                seq.block_table,
            ):
                if self.kv_flush is not None and self.kv_flush():
                    continue  # export pins released; retry before
                    # preempting anyone (flush empties the queue, so
                    # the second pass cannot loop here)
                victim = self._pick_preemption_victim(exclude=seq)
                if victim is None:
                    if len(self.running) == 1:
                        # a lone sequence has outgrown the entire pool;
                        # abort it rather than deadlocking the step loop
                        logger.error(
                            "request %s outgrew the KV pool (%d tokens); "
                            "aborting", seq.request_id, seq.num_tokens,
                        )
                        seq.status = SequenceStatus.FINISHED_ABORTED
                        self.free_finished(seq)
                        out.aborted.append(seq)
                        break
                    victim = seq
                self._preempt(victim, out)
                if victim in decode_seqs:
                    decode_seqs.remove(victim)
                if victim is seq:
                    break
            else:
                decode_seqs.append(seq)
        return decode_seqs

    # stackcheck: hot-path — pure host planning of the lane-typed round
    # on the scheduling path: one pass over running, no device work
    def plan_ragged_round(self, out: SchedulerOutput) -> SchedulerOutput:
        """Plan ONE lane-typed round (unified ragged dispatch): the
        decode-ready batch claims the decode lanes and every mid-prefill
        runner's next chunk claims a prefill lane IN THE SAME ROUND — a
        freshly admitted prompt is dispatched on the very next round
        with no interleave-streak wait, which is the scheduling contract
        tests/test_ragged_dispatch.py pins. The decode-capacity pass
        (with its preemption) runs FIRST so a victim never also claims a
        prefill lane; pick_decode_k's ragged branch drops the
        admission-K clamp for in-round prefill work (only a
        capacity-starved waiting queue still clamps)."""
        decode_seqs = self._collect_decode_ready(out)
        group_cap = (
            self.config.max_prefill_seqs
            if self.config.enable_chunked_prefill
            else 1
        )
        for seq in self.running:
            if seq.prefill_done or seq.finished or seq.long_prefill_active:
                # long-lane sequences never claim a ragged prefill lane
                # (the engine rings them one enqueue per step)
                continue
            if len(out.prefills) >= group_cap:
                break
            chunk_len = seq.num_uncomputed_prompt_tokens
            if self.config.enable_chunked_prefill:
                chunk_len = min(chunk_len, self.config.max_prefill_chunk)
            out.prefills.append(PrefillWork(
                seq=seq,
                chunk_start=seq.num_computed_tokens,
                chunk_len=chunk_len,
            ))
        if decode_seqs:
            out.decode = DecodeWork(
                seqs=decode_seqs, k=self.pick_decode_k(decode_seqs)
            )
        return out

    # K clamp while admission work exists: a fused round never keeps a
    # cold prompt waiting for more than ~this many steps (the K=16
    # TTFT-blowup failure mode was 16 uninterruptible steps per round
    # while prefill chunks queued — PERF.md round 5 window 2)
    ADMISSION_K_CLAMP = 2

    # stackcheck: hot-path — pure host arithmetic on the scheduling
    # path; one pass over the decode batch, no allocation beyond ints
    def pick_decode_k(
        self, seqs: list[Sequence], advance: int = 0
    ) -> int:
        """Size this round's fused decode K (elastic fused decode):
        pow2 buckets up to decode_k_cap, clamped to ADMISSION_K_CLAMP
        while any prefill work is pending (waiting queue or a running
        mid-prefill sequence — admission must never be starved by a
        long uninterruptible round), and bounded by the batch's MAX
        remaining-token budget (when every lane has <=4 tokens left, a
        K=16 dispatch wastes 3/4 of its slots — the K=32 overshoot
        mode; under device stops the shorter lanes freeze mid-round
        anyway, so the max is the right bound). `advance` predicts the
        pick `advance` tokens ahead (h2d prefetch stages the NEXT
        round before this one's tokens are applied). Returns the cap
        unchanged with adaptive K off."""
        cap = max(1, self.config.decode_k_cap)
        if not self.config.adaptive_decode_k or cap == 1 or not seqs:
            return cap
        k = cap
        if self.config.ragged_dispatch:
            # ragged audit: a mid-prefill runner rides THIS round as a
            # prefill lane, so it must not clamp K — that was exactly
            # the interleave-era starvation the unified round dissolves.
            # Only a capacity-starved waiting queue (admission loop left
            # it non-empty) still clamps: a shorter round reaches the
            # next admission/preemption decision sooner.
            if self.waiting:
                k = min(k, self.ADMISSION_K_CLAMP)
        elif self.waiting or any(
            not s.prefill_done and not s.long_prefill_active
            for s in self.running
        ):
            # a long-lane runner is mid-prefill for SECONDS (the whole
            # ring) and advances one enqueue per step regardless of K —
            # clamping every decode round under it was exactly the
            # starvation the lane exists to avoid
            k = min(k, self.ADMISSION_K_CLAMP)
        rem = 0
        mml = self.config.max_model_len
        for s in seqs:
            sp = s.sampling_params
            r = min(
                sp.max_tokens - len(s.generated_token_ids),
                mml - s.num_tokens,
            ) - advance
            rem = max(rem, r)
        rem = max(1, rem)
        if rem < k:
            # round UP to the pow2 bucket so the variant space stays
            # O(log cap) (precompiled by --precompile-serving)
            k = 1 << (rem - 1).bit_length()
        return max(1, min(k, cap))

    def _note_admitted(self, seq: Sequence) -> None:
        """Queue-wait/stall bookkeeping + timeline event on each
        WAITING/PREEMPTED -> RUNNING transition. Admission is off the
        device-dispatch path, so the time.time() stamps here are free."""
        now = time.time()
        m = seq.metrics
        resumed = m.last_preempt_time is not None
        if resumed:
            m.preempt_stall_s += now - m.last_preempt_time
            m.last_preempt_time = None
        if m.admitted_time is None:
            m.admitted_time = now
        tl = self.timeline
        if tl is not None and tl.enabled:
            tl.event(
                seq.request_id,
                "resume" if resumed else "admit",
                {
                    "queue_wait_s": round(now - m.arrival_time, 6),
                    "cached_prompt_tokens": m.num_cached_prompt_tokens,
                    **(
                        {"stall_s": round(m.preempt_stall_s, 6)}
                        if resumed else {}
                    ),
                },
            )

    def note_staged_prefill_miss(self) -> None:
        """The engine found the staged prefill buffer stale at dispatch
        time (fingerprint mismatch): the dispatch paid the full serial
        h2d after all, so convert the zero-cost admission back into a
        normally charged one."""
        if self._staged_run > 0:
            self._staged_run -= 1
            self._prefill_streak += 1

    def schedule_admit_retry(self, out: SchedulerOutput) -> SchedulerOutput:
        """Re-run schedule() after a priority claim, merging the
        preemption bookkeeping into the same step's output."""
        nxt = self.schedule()
        nxt.preempted = out.preempted + nxt.preempted
        nxt.aborted = out.aborted + nxt.aborted
        return nxt

    def _eviction_can_fit(self, cand: Sequence) -> bool:
        """Feasibility gate before ANY priority eviction: evicting every
        strictly lower-standing runner must free enough blocks for
        `cand`'s minimum allocation — otherwise victims would lose
        their KV progress while the claimed lane sits idle (the freed
        capacity can never admit cand, and lower-priority waiters must
        not jump it under strict priority)."""
        bs = self.block_manager.block_size
        need = (cand.num_prompt_tokens + 1 + bs - 1) // bs
        if (self.block_manager.enable_prefix_caching
                and cand.sampling_params.prompt_logprobs is None):
            # shared cached prefix blocks cost no new allocation (same
            # cap as allocate_prompt: at least one token computes)
            _, cached_tokens = self.block_manager.match_prefix(
                cand.prompt_token_ids, cand.hash_seed
            )
            cached_tokens = min(
                cached_tokens, cand.num_prompt_tokens - 1
            )
            need -= cached_tokens // bs
        avail = self.block_manager.num_free_blocks
        ck = (cand.priority, cand.arrival_ordinal)
        for s in self.running:
            if (s.priority, s.arrival_ordinal) > ck:
                avail += len(s.block_table)
        return avail >= need

    def _priority_preempt_for(
        self, seq: Sequence, out: SchedulerOutput
    ) -> bool:
        """Block-shortage variant of the priority claim: free blocks by
        evicting a strictly lower-standing RUNNING sequence so `seq`
        can allocate. Returns True when a victim was preempted."""
        if self.config.scheduling_policy != "priority" or not self.running:
            return False
        if not self._eviction_can_fit(seq):
            return False
        worst = max(
            self.running,
            key=lambda s: (s.priority, s.arrival_ordinal),
        )
        if (seq.priority, seq.arrival_ordinal) < (
            worst.priority, worst.arrival_ordinal
        ):
            self._preempt(worst, out)
            return True
        return False

    def _pick_preemption_victim(self, exclude: Sequence) -> Sequence | None:
        if self.config.scheduling_policy == "priority":
            # evict the LOWEST-priority running sequence (largest value),
            # youngest among ties — a high-priority request must not be
            # recomputed to make room for a low-priority one. If the
            # REQUESTER itself is the lowest-standing sequence, return
            # None so it self-preempts instead of evicting a
            # higher-priority neighbour.
            best = None
            for seq in self.running:
                if seq is exclude:
                    continue
                key = (seq.priority, seq.arrival_ordinal)
                if best is None or key > (best.priority,
                                          best.arrival_ordinal):
                    best = seq
            if best is not None and (
                (best.priority, best.arrival_ordinal)
                > (exclude.priority, exclude.arrival_ordinal)
            ):
                return best
            return None
        for seq in reversed(self.running):  # youngest first
            if seq is not exclude:
                return seq
        return None

    def _preempt(self, seq: Sequence, out: SchedulerOutput) -> None:
        logger.info("preempting request %s (recompute)", seq.request_id)
        self.running.remove(seq)
        self.block_manager.free(seq.block_table)
        seq.reset_for_recompute()
        self.waiting.appendleft(seq)
        out.preempted.append(seq)
        tl = self.timeline
        if tl is not None and tl.enabled:
            tl.event(
                seq.request_id, "preempt",
                {"num_preemptions": seq.metrics.num_preemptions},
            )
