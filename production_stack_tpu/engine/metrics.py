"""Engine Prometheus metrics.

Gauge names follow the exact contract the reference router scrapes
(reference: src/vllm_router/stats/engine_stats.py:63-76 parses
`vllm:num_requests_running`, `vllm:num_requests_waiting`,
`vllm:gpu_cache_usage_perc`, `vllm:gpu_prefix_cache_hit_rate`,
`vllm:gpu_prefix_cache_{hits,queries}_total`), so any router/dashboard built
for vLLM engines scrapes ours unchanged. On TPU the "gpu_" prefix is kept for
drop-in compatibility; tpu:* aliases are exported alongside.
"""

from __future__ import annotations

from prometheus_client import (
    REGISTRY,
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
)

from production_stack_tpu.engine.outputs import EngineStatsSnapshot

_LATENCY_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.5, 3.0, 6.0, 12.0, 30.0, 60.0,
)


class EngineMetrics:
    def __init__(
        self,
        model_name: str,
        registry: CollectorRegistry | None = None,
    ):
        self.model_name = model_name
        reg = registry or REGISTRY
        label = ["model_name"]

        def gauge(name, doc):
            return Gauge(name, doc, label, registry=reg)

        self.num_running = gauge(
            "vllm:num_requests_running", "Requests currently being decoded"
        )
        self.num_waiting = gauge(
            "vllm:num_requests_waiting", "Requests waiting to be scheduled"
        )
        self.cache_usage = gauge(
            "vllm:gpu_cache_usage_perc", "KV-cache usage (1 = full)"
        )
        self.prefix_hit_rate = gauge(
            "vllm:gpu_prefix_cache_hit_rate",
            "Prefix-cache hit rate over engine lifetime",
        )
        self.prefix_hits = gauge(
            "vllm:gpu_prefix_cache_hits_total",
            "Prefix-cache token hits (total)",
        )
        self.prefix_queries = gauge(
            "vllm:gpu_prefix_cache_queries_total",
            "Prefix-cache token queries (total)",
        )
        # TPU-native aliases (the Grafana dashboard panels use either)
        self.tpu_cache_usage = gauge(
            "tpu:hbm_kv_cache_usage_perc", "KV-cache usage in TPU HBM"
        )
        self.prompt_tokens = Counter(
            "vllm:prompt_tokens", "Prefill tokens processed",
            label, registry=reg,
        )
        self.generation_tokens = Counter(
            "vllm:generation_tokens", "Tokens generated",
            label, registry=reg,
        )
        self.preemptions = Counter(
            "vllm:num_preemptions", "Sequence preemptions",
            label, registry=reg,
        )
        self.spec_drafts = Counter(
            "vllm:spec_decode_num_draft_tokens",
            "Speculative draft tokens proposed", label, registry=reg,
        )
        self.spec_accepted = Counter(
            "vllm:spec_decode_num_accepted_tokens",
            "Speculative draft tokens accepted", label, registry=reg,
        )
        # pipelined-prefill attribution (tpu-native): wall seconds per
        # phase of the prefill dispatch path + staging effectiveness,
        # so a dashboard can see WHERE prefill time goes (prep / h2d /
        # dispatch / fetch) and whether the h2d overlap is landing
        self.prefill_prep_s = Counter(
            "tpu:prefill_prep_seconds", "Prefill host-prep wall time",
            label, registry=reg,
        )
        self.prefill_h2d_s = Counter(
            "tpu:prefill_h2d_seconds",
            "Prefill host->device upload wall time", label, registry=reg,
        )
        self.prefill_dispatch_s = Counter(
            "tpu:prefill_dispatch_seconds",
            "Prefill dispatch-enqueue wall time", label, registry=reg,
        )
        self.prefill_fetch_s = Counter(
            "tpu:prefill_fetch_seconds",
            "Prefill device->host fetch wall time", label, registry=reg,
        )
        self.prefill_staged_hits = Counter(
            "tpu:prefill_staged_hits",
            "Prefill dispatches served from a pre-uploaded staged "
            "buffer", label, registry=reg,
        )
        self.prefill_staged_misses = Counter(
            "tpu:prefill_staged_misses",
            "Staged prefill buffers invalidated before dispatch",
            label, registry=reg,
        )
        self.prefill_chained_chunks = Counter(
            "tpu:prefill_chained_chunks",
            "Prefill chunks dispatched via cold-prompt chaining "
            "(no host round-trip between chunks)", label, registry=reg,
        )
        # long-prefill lane (context-parallel ring prefill): per-phase
        # TTFT attribution for prompts served by the sp-sharded ring —
        # ring compute, device->host KV materialization, paged-cache
        # landing, and the tier-export overflow that ran under the job
        self.long_prefill_requests = Counter(
            "tpu:long_prefill_requests",
            "Prompts served via the context-parallel ring prefill lane",
            label, registry=reg,
        )
        self.long_prefill_chunks = Counter(
            "tpu:long_prefill_chunks",
            "Ring prefill chunk dispatches", label, registry=reg,
        )
        self.long_prefill_fallbacks = Counter(
            "tpu:long_prefill_fallbacks",
            "Long prefills that failed back to chunked prefill",
            label, registry=reg,
        )
        self.prefill_ring_s = Counter(
            "tpu:prefill_ring_seconds",
            "Long-prefill ring compute wall time (job start -> ring "
            "drained; overlaps other users' decode rounds)",
            label, registry=reg,
        )
        self.prefill_ring_d2h_s = Counter(
            "tpu:prefill_ring_d2h_seconds",
            "Long-prefill device->host KV materialization wall time "
            "(on the long-prefill worker)", label, registry=reg,
        )
        self.prefill_kv_land_s = Counter(
            "tpu:prefill_kv_land_seconds",
            "Long-prefill paged-cache landing wall time (first parked "
            "batch -> last donated import enqueued)",
            label, registry=reg,
        )
        self.prefill_overflow_export_s = Counter(
            "tpu:prefill_overflow_export_seconds",
            "Tier-export seconds attributed to in-flight long prefills "
            "(HBM headroom the landed chain displaced)",
            label, registry=reg,
        )
        # zero-stall KV tiering (PR 4): deferred-export batch wall time
        # (measured ON THE OFFLOAD WORKER — overlapped activity, never a
        # step-loop stall), staged-restore enqueue->landed time, and
        # per-tier traffic so a dashboard can see WHICH tier serves and
        # whether eviction cascades are healthy
        _kv_buckets = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0)
        self.kv_export_s = Histogram(
            "tpu:kv_export_seconds",
            "Deferred KV export batch wall time (d2h materialization + "
            "tier store, on the offload worker)",
            label, buckets=_kv_buckets, registry=reg,
        )
        self.kv_restore_s = Histogram(
            "tpu:kv_restore_seconds",
            "Staged KV restore wall time (enqueue -> blocks landed in "
            "HBM; overlaps the request's queue wait)",
            label, buckets=_kv_buckets, registry=reg,
        )
        tier_label = ["model_name", "tier"]
        self.kv_tier_hits = Counter(
            "tpu:kv_tier_hits", "KV tier read hits",
            tier_label, registry=reg,
        )
        self.kv_tier_misses = Counter(
            "tpu:kv_tier_misses", "KV tier read misses (consulted tier "
            "did not hold the block)", tier_label, registry=reg,
        )
        self.kv_tier_read_bytes = Counter(
            "tpu:kv_tier_read_bytes", "Bytes served from a KV tier",
            tier_label, registry=reg,
        )
        self.kv_tier_write_bytes = Counter(
            "tpu:kv_tier_write_bytes", "Bytes admitted into a KV tier",
            tier_label, registry=reg,
        )
        self.kv_export_blocks = Counter(
            "tpu:kv_export_blocks", "KV blocks exported to the offload "
            "tiers", label, registry=reg,
        )
        self.kv_restore_blocks = Counter(
            "tpu:kv_restore_blocks", "KV blocks restored from the "
            "offload tiers into HBM", label, registry=reg,
        )
        self.kv_restore_fallbacks = Counter(
            "tpu:kv_restore_fallbacks", "Staged restores that fell back "
            "to recompute (broken chain, timeout, or full HBM)",
            label, registry=reg,
        )
        self.kv_export_sync_fallbacks = Counter(
            "tpu:kv_export_sync_fallbacks",
            "Deferred exports forced synchronous by the device-buffer "
            "backlog cap (tier IO slower than eviction churn)",
            label, registry=reg,
        )
        # disaggregated prefill/decode transfer (PeerTier pulls):
        # blocks the PD peer served / could not serve, bytes over the
        # transfer link, and failed pulls (dead peer, corrupt frame)
        self.kv_peer_hits = Counter(
            "tpu:kv_peer_hits",
            "KV blocks pulled from the disaggregated-prefill peer",
            label, registry=reg,
        )
        self.kv_peer_misses = Counter(
            "tpu:kv_peer_misses",
            "KV blocks requested from the PD peer but not served "
            "(chain evicted or never prefilled there)",
            label, registry=reg,
        )
        self.kv_peer_read_bytes = Counter(
            "tpu:kv_peer_read_bytes",
            "Bytes pulled over the inter-engine KV transfer link",
            label, registry=reg,
        )
        self.kv_peer_fallbacks = Counter(
            "tpu:kv_peer_fallbacks",
            "Failed PD peer pulls (dead peer / mid-frame death / "
            "corrupt payload) that degraded to local recompute",
            label, registry=reg,
        )
        # cluster-wide shared KV cache (RemoteTier <-> kv.cache_server):
        # cross-engine chain hits/misses, wire bytes each direction,
        # write-behind put_batch frames, and failed flushes/pulls
        self.kv_remote_hits = Counter(
            "tpu:kv_remote_hits",
            "KV blocks served by the shared cache server",
            label, registry=reg,
        )
        self.kv_remote_misses = Counter(
            "tpu:kv_remote_misses",
            "KV blocks requested from the shared cache server but not "
            "held there (cold chain or evicted/expired)",
            label, registry=reg,
        )
        self.kv_remote_read_bytes = Counter(
            "tpu:kv_remote_read_bytes",
            "Bytes pulled from the shared cache server",
            label, registry=reg,
        )
        self.kv_remote_write_bytes = Counter(
            "tpu:kv_remote_write_bytes",
            "Bytes shipped to the shared cache server (write-behind "
            "batched puts)",
            label, registry=reg,
        )
        self.kv_remote_flushes = Counter(
            "tpu:kv_remote_flushes",
            "Write-behind put_batch frames shipped to the shared cache",
            label, registry=reg,
        )
        self.kv_remote_fallbacks = Counter(
            "tpu:kv_remote_fallbacks",
            "Failed shared-cache flushes/pulls (dead server / corrupt "
            "frame) that degraded without stalling the engine",
            label, registry=reg,
        )
        # elastic fused decode: per-round chosen K (adaptive sizing in
        # pow2 buckets up to num_scheduler_steps), host-discarded
        # overshoot tokens (the K=32 waste mode — ~0 under device
        # stops), and whole-round device early exits
        self.decode_k = Histogram(
            "tpu:decode_k",
            "Fused decode iterations dispatched per round (adaptive K "
            "buckets; the cap with --no-adaptive-decode-k)",
            label, buckets=(1, 2, 4, 8, 16, 32), registry=reg,
        )
        self.decode_rounds = Counter(
            "tpu:decode_rounds", "Decode rounds dispatched",
            label, registry=reg,
        )
        self.decode_overshoot = Counter(
            "tpu:decode_overshoot_tokens",
            "Sampled decode slots discarded by the host past a stop "
            "condition (device stops freeze these lanes on device "
            "instead; stop STRINGS still resolve host-side)",
            label, registry=reg,
        )
        self.decode_early_exits = Counter(
            "tpu:decode_early_exit_rounds",
            "Fused decode rounds whose device loop exited before the "
            "trip count because every lane had finished",
            label, registry=reg,
        )
        # unified ragged dispatch: fused lane-typed rounds and their
        # lane mix (prefill lanes per fused round — pure rounds are not
        # observed, so rate(tpu:ragged_rounds) over
        # rate(tpu:decode_rounds) is the mixed-round share)
        self.ragged_lane_mix = Histogram(
            "tpu:ragged_lane_mix",
            "Prefill-chunk lanes fused into a ragged round (each "
            "observation is one mixed prefill+decode dispatch)",
            label, buckets=(1, 2, 4, 8, 16), registry=reg,
        )
        self.ragged_rounds = Counter(
            "tpu:ragged_rounds",
            "Lane-typed ragged rounds dispatched fused (prefill chunks "
            "+ decode steps in one device program)",
            label, registry=reg,
        )
        self.ragged_split_rounds = Counter(
            "tpu:ragged_split_rounds",
            "Planned mixed rounds executed as split prefill+decode "
            "dispatches (prompt_logprobs / host-sampled finals / "
            "near-budget guided lanes)",
            label, registry=reg,
        )
        self.compile_events = Counter(
            "tpu:compile_events_total",
            "Program-variant builds (jit cache misses on the model "
            "runner's step builders) — the cold-start compile tax, "
            "labeled by builder kind (decode_multi, ragged_rows, ...)",
            ["model_name", "kind"], registry=reg,
        )
        self.request_success = Counter(
            "vllm:request_success", "Finished requests",
            ["model_name", "finished_reason"], registry=reg,
        )
        self.ttft = Histogram(
            "vllm:time_to_first_token_seconds", "TTFT",
            label, buckets=_LATENCY_BUCKETS, registry=reg,
        )
        self.tpot = Histogram(
            "vllm:time_per_output_token_seconds", "Inter-token latency",
            label, buckets=(0.002, 0.005, 0.01, 0.02, 0.04, 0.08, 0.16,
                            0.32, 0.64, 1.28), registry=reg,
        )
        self.e2e_latency = Histogram(
            "vllm:e2e_request_latency_seconds", "End-to-end request latency",
            label, buckets=_LATENCY_BUCKETS, registry=reg,
        )
        # request-lifecycle attribution (fed from RequestMetrics at
        # finish): TTFT = queue-wait + scheduling delay + prefill, and
        # these split the first two out so a TTFT regression is
        # attributable without reading per-request timelines
        self.queue_time = Histogram(
            "tpu:request_queue_seconds",
            "Enqueue -> scheduler admission (waiting-queue wait)",
            label, buckets=_LATENCY_BUCKETS, registry=reg,
        )
        self.sched_delay = Histogram(
            "tpu:scheduling_delay_seconds",
            "Scheduler admission -> first prefill dispatch",
            label, buckets=_LATENCY_BUCKETS, registry=reg,
        )
        self.preempt_stall = Histogram(
            "tpu:preemption_stall_seconds",
            "Wall time spent preempted (preempt -> re-admission), "
            "summed per request; observed only for preempted requests",
            label, buckets=_LATENCY_BUCKETS, registry=reg,
        )
        self._counter_state = EngineStatsSnapshot()

    def update_from_snapshot(self, s: EngineStatsSnapshot) -> None:
        m = self.model_name
        self.num_running.labels(m).set(s.num_running)
        self.num_waiting.labels(m).set(s.num_waiting)
        self.cache_usage.labels(m).set(s.kv_usage)
        self.tpu_cache_usage.labels(m).set(s.kv_usage)
        self.prefix_hit_rate.labels(m).set(s.prefix_cache_hit_rate)
        self.prefix_hits.labels(m).set(s.prefix_cache_hits)
        self.prefix_queries.labels(m).set(s.prefix_cache_queries)
        prev = self._counter_state
        self.prompt_tokens.labels(m).inc(
            max(0, s.prompt_tokens_total - prev.prompt_tokens_total)
        )
        self.generation_tokens.labels(m).inc(
            max(0, s.generation_tokens_total - prev.generation_tokens_total)
        )
        self.preemptions.labels(m).inc(
            max(0, s.num_preemptions_total - prev.num_preemptions_total)
        )
        self.spec_drafts.labels(m).inc(
            max(0, s.spec_draft_tokens_total
                - prev.spec_draft_tokens_total)
        )
        self.spec_accepted.labels(m).inc(
            max(0, s.spec_accepted_tokens_total
                - prev.spec_accepted_tokens_total)
        )
        self.prefill_prep_s.labels(m).inc(max(
            0.0, s.prefill_prep_seconds_total
            - prev.prefill_prep_seconds_total))
        self.prefill_h2d_s.labels(m).inc(max(
            0.0, s.prefill_h2d_seconds_total
            - prev.prefill_h2d_seconds_total))
        self.prefill_dispatch_s.labels(m).inc(max(
            0.0, s.prefill_dispatch_seconds_total
            - prev.prefill_dispatch_seconds_total))
        self.prefill_fetch_s.labels(m).inc(max(
            0.0, s.prefill_fetch_seconds_total
            - prev.prefill_fetch_seconds_total))
        self.prefill_staged_hits.labels(m).inc(max(
            0, s.prefill_staged_hits_total
            - prev.prefill_staged_hits_total))
        self.prefill_staged_misses.labels(m).inc(max(
            0, s.prefill_staged_misses_total
            - prev.prefill_staged_misses_total))
        self.prefill_chained_chunks.labels(m).inc(max(
            0, s.prefill_chained_chunks_total
            - prev.prefill_chained_chunks_total))
        self.long_prefill_requests.labels(m).inc(max(
            0, s.long_prefill_requests_total
            - prev.long_prefill_requests_total))
        self.long_prefill_chunks.labels(m).inc(max(
            0, s.long_prefill_chunks_total
            - prev.long_prefill_chunks_total))
        self.long_prefill_fallbacks.labels(m).inc(max(
            0, s.long_prefill_fallbacks_total
            - prev.long_prefill_fallbacks_total))
        self.prefill_ring_s.labels(m).inc(max(
            0.0, s.long_prefill_ring_seconds_total
            - prev.long_prefill_ring_seconds_total))
        self.prefill_ring_d2h_s.labels(m).inc(max(
            0.0, s.long_prefill_d2h_seconds_total
            - prev.long_prefill_d2h_seconds_total))
        self.prefill_kv_land_s.labels(m).inc(max(
            0.0, s.long_prefill_land_seconds_total
            - prev.long_prefill_land_seconds_total))
        self.prefill_overflow_export_s.labels(m).inc(max(
            0.0, s.long_prefill_overflow_seconds_total
            - prev.long_prefill_overflow_seconds_total))
        self.decode_rounds.labels(m).inc(max(
            0, s.decode_rounds_total - prev.decode_rounds_total))
        self.decode_overshoot.labels(m).inc(max(
            0, s.decode_overshoot_tokens_total
            - prev.decode_overshoot_tokens_total))
        self.decode_early_exits.labels(m).inc(max(
            0, s.decode_early_exit_rounds_total
            - prev.decode_early_exit_rounds_total))
        self.ragged_rounds.labels(m).inc(max(
            0, s.ragged_rounds_total - prev.ragged_rounds_total))
        self.ragged_split_rounds.labels(m).inc(max(
            0, s.ragged_split_rounds_total
            - prev.ragged_split_rounds_total))
        for kind, n in (s.compile_events or {}).items():
            pn = (prev.compile_events or {}).get(kind, 0)
            self.compile_events.labels(m, kind).inc(max(0, n - pn))
        self.kv_export_blocks.labels(m).inc(max(
            0, s.kv_export_blocks_total - prev.kv_export_blocks_total))
        self.kv_restore_blocks.labels(m).inc(max(
            0, s.kv_restore_blocks_total - prev.kv_restore_blocks_total))
        self.kv_restore_fallbacks.labels(m).inc(max(
            0, s.kv_restore_fallbacks_total
            - prev.kv_restore_fallbacks_total))
        self.kv_export_sync_fallbacks.labels(m).inc(max(
            0, s.kv_export_sync_fallbacks_total
            - prev.kv_export_sync_fallbacks_total))
        self.kv_peer_hits.labels(m).inc(max(
            0, s.kv_peer_hits_total - prev.kv_peer_hits_total))
        self.kv_peer_misses.labels(m).inc(max(
            0, s.kv_peer_misses_total - prev.kv_peer_misses_total))
        self.kv_peer_read_bytes.labels(m).inc(max(
            0, s.kv_peer_read_bytes_total
            - prev.kv_peer_read_bytes_total))
        self.kv_peer_fallbacks.labels(m).inc(max(
            0, s.kv_peer_fallbacks_total
            - prev.kv_peer_fallbacks_total))
        self.kv_remote_hits.labels(m).inc(max(
            0, s.kv_remote_hits_total - prev.kv_remote_hits_total))
        self.kv_remote_misses.labels(m).inc(max(
            0, s.kv_remote_misses_total - prev.kv_remote_misses_total))
        self.kv_remote_read_bytes.labels(m).inc(max(
            0, s.kv_remote_read_bytes_total
            - prev.kv_remote_read_bytes_total))
        self.kv_remote_write_bytes.labels(m).inc(max(
            0, s.kv_remote_write_bytes_total
            - prev.kv_remote_write_bytes_total))
        self.kv_remote_flushes.labels(m).inc(max(
            0, s.kv_remote_flushes_total
            - prev.kv_remote_flushes_total))
        self.kv_remote_fallbacks.labels(m).inc(max(
            0, s.kv_remote_fallbacks_total
            - prev.kv_remote_fallbacks_total))
        for tier, c in (s.kv_tier_counters or {}).items():
            pc = (prev.kv_tier_counters or {}).get(tier, {})
            self.kv_tier_hits.labels(m, tier).inc(
                max(0, c.get("hits", 0) - pc.get("hits", 0)))
            self.kv_tier_misses.labels(m, tier).inc(
                max(0, c.get("misses", 0) - pc.get("misses", 0)))
            self.kv_tier_read_bytes.labels(m, tier).inc(
                max(0, c.get("read_bytes", 0) - pc.get("read_bytes", 0)))
            self.kv_tier_write_bytes.labels(m, tier).inc(
                max(0, c.get("write_bytes", 0)
                    - pc.get("write_bytes", 0)))
        self._counter_state = s

    def observe_kv(
        self,
        export_seconds: list[float],
        restore_seconds: list[float],
    ) -> None:
        """Feed drained engine observations (LLMEngine.
        drain_kv_observations) into the tpu:kv_*_seconds histograms."""
        m = self.model_name
        for s in export_seconds:
            self.kv_export_s.labels(m).observe(max(0.0, s))
        for s in restore_seconds:
            self.kv_restore_s.labels(m).observe(max(0.0, s))

    def observe_decode_k(self, ks: list[int]) -> None:
        """Feed drained chosen-K observations (LLMEngine.
        drain_decode_k_observations) into the tpu:decode_k histogram."""
        m = self.model_name
        for k in ks:
            self.decode_k.labels(m).observe(k)

    def observe_ragged(self, lane_counts: list[int]) -> None:
        """Feed drained ragged lane-mix observations (LLMEngine.
        drain_ragged_observations — prefill lanes per fused round)
        into the tpu:ragged_lane_mix histogram."""
        m = self.model_name
        for n in lane_counts:
            self.ragged_lane_mix.labels(m).observe(n)

    def observe_request(
        self,
        finish_reason: str,
        ttft_s: float | None,
        e2e_s: float | None,
        n_output_tokens: int,
        queue_s: float | None = None,
        sched_delay_s: float | None = None,
        preempt_stall_s: float | None = None,
    ) -> None:
        m = self.model_name
        self.request_success.labels(m, finish_reason).inc()
        if ttft_s is not None:
            self.ttft.labels(m).observe(ttft_s)
        if e2e_s is not None:
            self.e2e_latency.labels(m).observe(e2e_s)
            if ttft_s is not None and n_output_tokens > 1:
                self.tpot.labels(m).observe(
                    (e2e_s - ttft_s) / (n_output_tokens - 1)
                )
        if queue_s is not None:
            self.queue_time.labels(m).observe(max(0.0, queue_s))
        if sched_delay_s is not None:
            self.sched_delay.labels(m).observe(max(0.0, sched_delay_s))
        if preempt_stall_s is not None:
            # only preempted requests observe (a zero-flood would bury
            # the signal); panels rate() over preemption events
            self.preempt_stall.labels(m).observe(max(0.0, preempt_stall_s))
