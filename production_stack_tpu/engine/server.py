"""OpenAI-compatible HTTP server for the TPU engine (aiohttp.web).

API surface parity with the vLLM engine pods the reference deploys
(reference: helm/templates/deployment-vllm-multi.yaml:104-126 runs
`vllm serve`; the router proxies these endpoints, reference:
src/vllm_router/routers/main_router.py:45-231):

  POST /v1/completions            streaming + blocking
  POST /v1/chat/completions       streaming + blocking
  GET  /v1/models
  POST /tokenize /detokenize
  GET  /health /version /metrics
  POST /sleep /wake_up  GET /is_sleeping
  POST /v1/load_lora_adapter /v1/unload_lora_adapter

The Prometheus /metrics endpoint exports the exact vllm:* gauge names the
router's stats scraper parses (see engine/metrics.py).
"""

from __future__ import annotations

import asyncio
import json
import time

from aiohttp import web
from prometheus_client import CollectorRegistry, generate_latest

import production_stack_tpu
from production_stack_tpu.engine.async_engine import (
    AsyncLLMEngine,
    EngineSleepingError,
)
from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.metrics import EngineMetrics
from production_stack_tpu.engine import protocol as proto
from production_stack_tpu.engine import tools
from production_stack_tpu.engine.sampling_params import SamplingParams
from production_stack_tpu.tracing import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    log_otlp_payload,
    otlp_flush_loop,
    valid_request_id,
)
from production_stack_tpu.utils import init_logger
from production_stack_tpu.utils.tasks import spawn_watched

logger = init_logger(__name__)

STATS_UPDATE_INTERVAL_S = 1.0


class EngineServer:
    def __init__(self, config: EngineConfig, params: dict | None = None):
        self.config = config
        self.model_name = config.served_model_name or config.model
        self.engine = AsyncLLMEngine(config, params=params)
        if config.precompile_serving:
            t0 = time.time()
            n = self.engine.engine.precompile_serving()
            logger.info(
                "serving precompile: %d dispatches in %.1fs (every "
                "config-derivable program shape warm; only "
                "request-dependent sampling variants can still compile "
                "lazily)", n, time.time() - t0,
            )
        self.registry = CollectorRegistry()
        self.metrics = EngineMetrics(self.model_name, registry=self.registry)
        self.lora_adapters: dict[str, str] = {}  # name -> path
        self._stats_task: asyncio.Task | None = None
        self.app = self._build_app()

    # -- app wiring --------------------------------------------------------
    def _build_app(self) -> web.Application:
        middlewares = []
        if self.config.api_key:
            middlewares.append(self._auth_middleware)
        app = web.Application(
            client_max_size=64 * 2**20, middlewares=middlewares
        )
        r = app.router
        r.add_post("/v1/completions", self.handle_completions)
        r.add_post("/v1/chat/completions", self.handle_chat)
        r.add_get("/v1/models", self.handle_models)
        r.add_post("/v1/embeddings", self.handle_embeddings)
        r.add_post("/v1/rerank", self.handle_rerank)
        r.add_post("/rerank", self.handle_rerank)
        r.add_post("/v1/score", self.handle_score)
        r.add_post("/score", self.handle_score)
        r.add_post("/tokenize", self.handle_tokenize)
        r.add_post("/detokenize", self.handle_detokenize)
        r.add_get("/health", self.handle_health)
        r.add_get("/version", self.handle_version)
        r.add_get("/metrics", self.handle_metrics)
        r.add_get("/debug/requests", self.handle_debug_requests)
        r.add_post("/sleep", self.handle_sleep)
        r.add_post("/wake_up", self.handle_wake)
        r.add_get("/is_sleeping", self.handle_is_sleeping)
        r.add_post("/v1/load_lora_adapter", self.handle_load_lora)
        r.add_post("/v1/unload_lora_adapter", self.handle_unload_lora)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        """Bearer-token auth on the OpenAI surface (vLLM --api-key,
        reference tutorial 11-secure-vllm-serve). /health and /metrics
        stay open for probes and Prometheus."""
        if request.path.startswith("/v1/") or request.path in (
            "/tokenize", "/detokenize", "/sleep", "/wake_up",
            "/rerank", "/score",
        ):
            import hmac

            auth = request.headers.get("Authorization", "")
            # compare as bytes: compare_digest raises TypeError on
            # non-ASCII str input (reachable via latin-1 header bytes)
            if not hmac.compare_digest(
                auth.encode("utf-8", "surrogateescape"),
                f"Bearer {self.config.api_key}".encode(),
            ):
                return web.json_response(
                    proto.error_json("invalid API key",
                                     "authentication_error", 401),
                    status=401,
                )
        return await handler(request)

    async def _on_startup(self, app: web.Application) -> None:
        self.engine.start(asyncio.get_running_loop())
        self._stats_task = spawn_watched(self._stats_loop(), "engine-stats")
        if self.engine.tracer.exporter == "otlp":
            self._trace_flush_task = spawn_watched(
                otlp_flush_loop(self.engine.tracer), "engine-trace-flush"
            )
        # disaggregated prefill producer: serve KV block chains to
        # decode peers (reference: NIXL sender role,
        # LMCACHE_NIXL_ROLE=sender). prefill AND both roles serve —
        # a both-role engine can hand its chains to any peer.
        listen = (self.config.kv_transfer_config or {}).get("listen")
        if listen and self.config.pd_role() in ("prefill", "both"):
            from production_stack_tpu.kv import transfer
            from production_stack_tpu.kv.wire import parse_addr

            host, port = parse_addr(listen, transfer.DEFAULT_PORT)
            self._kv_transfer_server = transfer.KVTransferServer(self.engine)
            await self._kv_transfer_server.start(host or "0.0.0.0", port)

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._stats_task:
            self._stats_task.cancel()
        if getattr(self, "_trace_flush_task", None) is not None:
            self._trace_flush_task.cancel()
            # final drain: up to a flush interval of spans is still
            # buffered — a graceful stop must not drop them
            log_otlp_payload(self.engine.tracer)
        if getattr(self, "_kv_transfer_server", None) is not None:
            await self._kv_transfer_server.stop()
        self.engine.shutdown()

    async def _stats_loop(self) -> None:
        while True:
            try:
                self.metrics.update_from_snapshot(self.engine.stats())
                self.metrics.observe_kv(
                    *self.engine.drain_kv_observations()
                )
                self.metrics.observe_decode_k(
                    self.engine.drain_decode_k_observations()
                )
                self.metrics.observe_ragged(
                    self.engine.drain_ragged_observations()
                )
            except Exception:  # pragma: no cover
                logger.exception("stats update failed")
            await asyncio.sleep(STATS_UPDATE_INTERVAL_S)

    # -- helpers -----------------------------------------------------------
    async def _json_body(self, request: web.Request):
        """-> (body, None) or (None, 400-response); body is a dict."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            body = None
        if not isinstance(body, dict):
            return None, web.json_response(
                proto.error_json("request body must be a JSON object"),
                status=400,
            )
        return body, None

    def _check_model(self, body: dict) -> web.Response | None:
        model = body.get("model")
        if model and model not in (self.model_name, self.config.model) and (
            model not in self.lora_adapters
        ):
            return web.json_response(
                proto.error_json(f"model {model!r} not found", code=404),
                status=404,
            )
        return None

    def _apply_truncation(self, ids: list[int], sp) -> list[int]:
        """vLLM truncate_prompt_tokens, applied BEFORE the context-length
        gate — the feature exists to make over-long prompts fit."""
        from production_stack_tpu.engine.sampling_params import (
            truncate_prompt,
        )

        return truncate_prompt(
            ids, sp, self.config.resolved_max_model_len()
        )

    @staticmethod
    def _parse_priority(body: dict):
        """-> (priority, None) or (0, 400-response)."""
        try:
            return int(body.get("priority", 0)), None
        except (TypeError, ValueError):
            return 0, web.json_response(
                proto.error_json("priority must be an integer"),
                status=400,
            )

    def _observe_finish(self, out, arrival: float) -> None:
        m = out.metrics
        ttft = (
            m.first_token_time - arrival
            if m.first_token_time is not None
            else None
        )
        e2e = time.time() - arrival
        self.metrics.observe_request(
            out.finish_reason or "stop", ttft, e2e, len(out.token_ids),
            queue_s=(
                m.admitted_time - m.arrival_time
                if m.admitted_time is not None else None
            ),
            sched_delay_s=(
                m.first_scheduled_time - m.admitted_time
                if (m.first_scheduled_time is not None
                    and m.admitted_time is not None) else None
            ),
            preempt_stall_s=(
                m.preempt_stall_s if m.num_preemptions > 0 else None
            ),
        )

    # -- request identity + trace context ----------------------------------
    def _request_identity(
        self, request: web.Request, prefix: str
    ) -> tuple[str, str | None]:
        """(request_id, traceparent) for one inbound HTTP request.

        A router-supplied `x-request-id` becomes the ENGINE-side request
        id (and is echoed on the response) so logs, spans, and timelines
        join on one id end-to-end; ids failing the charset/length gate
        fall back to a fresh one. A supplied id that is still IN FLIGHT
        (client timeout-retry with a stable id, or two clients
        colliding) also falls back — correlation degrades for that
        retry, but the request is served instead of 400ing the way a
        hard duplicate would. The `traceparent` passes through verbatim
        — the timeline recorder validates it (malformed -> fresh
        trace)."""
        rid = request.headers.get(REQUEST_ID_HEADER)
        if (
            not valid_request_id(rid)
            or self.engine.has_request(rid)
            # multi-choice requests register per-choice `<rid>-c<i>`
            # sub-ids (any of which may still be running after others
            # finished), so a retried n>1 request collides on those
            or self.engine.has_request_prefix(rid)
        ):
            rid = proto.make_id(prefix)
        return rid, request.headers.get(TRACEPARENT_HEADER)

    # -- completions -------------------------------------------------------
    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        body, err = await self._json_body(request)
        if err is not None:
            return err
        err = self._check_model(body)
        if err is not None:
            return err
        prompt = body.get("prompt")
        if prompt is None:
            return web.json_response(
                proto.error_json("missing 'prompt'"), status=400
            )
        # OpenAI batch semantics: prompt may be one string, one token-id
        # list, or a list of either (choices index prompt_idx*n+sample)
        if isinstance(prompt, str):
            raw_prompts: list = [prompt]
        elif isinstance(prompt, list) and prompt and all(
            isinstance(x, int) for x in prompt
        ):
            raw_prompts = [prompt]
        elif isinstance(prompt, list) and prompt and all(
            isinstance(x, str)
            or (isinstance(x, list) and x
                and all(isinstance(t, int) for t in x))
            for x in prompt
        ):
            raw_prompts = list(prompt)
        else:
            return web.json_response(
                proto.error_json(
                    "'prompt' must be a string, a token-id list, or a "
                    "non-empty list of either"
                ),
                status=400,
            )
        try:
            sp = proto.sampling_params_from_request(body)
        except proto.ProtocolError as e:
            return web.json_response(proto.error_json(str(e)), status=400)
        if body.get("suffix"):
            # vLLM-parity: fill-in-the-middle is a model capability the
            # decoder-only serving path does not provide
            return web.json_response(
                proto.error_json("suffix is not supported"), status=400
            )
        best_of = body.get("best_of")
        try:
            best_of = int(best_of) if best_of is not None else None
        except (TypeError, ValueError):
            return web.json_response(
                proto.error_json("best_of must be an integer"), status=400
            )
        if best_of is not None and best_of != sp.n:
            return web.json_response(
                proto.error_json(
                    "best_of != n is not supported (use n-way sampling)"
                ),
                status=400,
            )
        req_priority, perr = self._parse_priority(body)
        if perr is not None:
            return perr
        echo = bool(body.get("echo", False))
        if echo and sp.logprobs is not None:
            return web.json_response(
                proto.error_json(
                    "echo with logprobs is not supported; request "
                    "prompt_logprobs for per-prompt-token logprobs"
                ),
                status=400,
            )

        request_id, traceparent = self._request_identity(request, "cmpl")
        prompt_ids_list: list[list[int]] = []
        for p in raw_prompts:
            ids = (
                list(p) if isinstance(p, list)
                else self.engine.tokenizer.encode(p)
            )
            ids = self._apply_truncation(ids, sp)
            err = self._check_context_len(ids)
            if err is not None:
                return err
            prompt_ids_list.append(ids)
        lora_name = body.get("model") if (
            body.get("model") in self.lora_adapters) else None
        # OpenAI echo: the response text leads with the prompt the
        # engine ACTUALLY processed — after truncation (string prompts
        # echo verbatim only when untruncated)
        echo_prefixes = None
        if echo:
            echo_prefixes = [
                p if (isinstance(p, str)
                      and sp.truncate_prompt_tokens is None)
                else self.engine.tokenizer.decode(list(ids))
                for p, ids in zip(raw_prompts, prompt_ids_list)
            ]

        if len(prompt_ids_list) * sp.n > 1:
            return await self._multi_completion(
                request, request_id, sp, prompt_ids_list, lora_name,
                chat=False, model=body.get("model") or self.model_name,
                stream=bool(body.get("stream")),
                include_usage=self._wants_usage(body),
                echo_prefixes=echo_prefixes,
                priority=req_priority,
                traceparent=traceparent,
            )
        kwargs = {"prompt_token_ids": prompt_ids_list[0],
                  "priority": req_priority,
                  "traceparent": traceparent}
        if body.get("stream"):
            return await self._stream_completion(
                request, request_id, sp, kwargs, lora_name, chat=False,
                include_usage=self._wants_usage(body),
                echo_prefix=echo_prefixes[0] if echo_prefixes else None,
            )
        return await self._blocking_completion(
            request_id, sp, kwargs, lora_name, chat=False,
            model=body.get("model") or self.model_name,
            echo_prefix=echo_prefixes[0] if echo_prefixes else None,
        )

    # -- chat --------------------------------------------------------------
    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        body, err = await self._json_body(request)
        if err is not None:
            return err
        err = self._check_model(body)
        if err is not None:
            return err
        messages = body.get("messages")
        if not messages:
            return web.json_response(
                proto.error_json("missing 'messages'"), status=400
            )
        req_tools = body.get("tools")
        tool_choice = body.get("tool_choice",
                               "auto" if req_tools else "none")
        use_tools = bool(req_tools) and tool_choice != "none"
        if use_tools and tool_choice == "auto" and not (
            self.config.enable_auto_tool_choice
        ):
            return web.json_response(
                proto.error_json(
                    "tools require --enable-auto-tool-choice (or a "
                    "named tool_choice)"
                ),
                status=400,
            )
        try:
            if use_tools:
                messages = tools.inject_tools(
                    messages, req_tools, tool_choice
                )
            prompt = self.engine.tokenizer.apply_chat_template(messages)
            sp = proto.sampling_params_from_request(body)
            if body.get("logprobs") is True:
                # chat form: logprobs: true + top_logprobs: N
                import dataclasses

                top_n = int(body.get("top_logprobs", 0) or 0)
                if not 0 <= top_n <= 20:
                    raise proto.ProtocolError(
                        "top_logprobs must be in [0, 20]"
                    )
                sp = dataclasses.replace(sp, logprobs=top_n)
        except (proto.ProtocolError, ValueError) as e:
            return web.json_response(proto.error_json(str(e)), status=400)
        except Exception as e:
            return web.json_response(
                proto.error_json(f"chat template error: {e}"), status=400
            )

        request_id, traceparent = self._request_identity(
            request, "chatcmpl"
        )
        prompt_ids = self.engine.tokenizer.encode(prompt)
        prompt_ids = self._apply_truncation(prompt_ids, sp)
        err = self._check_context_len(prompt_ids)
        if err is not None:
            return err
        req_priority, perr = self._parse_priority(body)
        if perr is not None:
            return perr
        lora_name = body.get("model") if (
            body.get("model") in self.lora_adapters) else None
        if sp.n > 1:
            return await self._multi_completion(
                request, request_id, sp, [prompt_ids], lora_name,
                chat=True, model=body.get("model") or self.model_name,
                stream=bool(body.get("stream")),
                include_usage=self._wants_usage(body),
                parse_tools=use_tools,
                priority=req_priority,
                traceparent=traceparent,
            )
        if body.get("stream"):
            # streamed responses pass tool-call text through verbatim
            # (parsing happens client-side); blocking mode parses
            return await self._stream_completion(
                request, request_id, sp,
                {"prompt_token_ids": prompt_ids,
                 "priority": req_priority,
                 "traceparent": traceparent},
                lora_name, chat=True,
                include_usage=self._wants_usage(body),
            )
        return await self._blocking_completion(
            request_id, sp,
            {"prompt_token_ids": prompt_ids, "priority": req_priority,
             "traceparent": traceparent},
            lora_name,
            chat=True,
            model=body.get("model") or self.model_name,
            parse_tools=use_tools,
        )

    # -- shared generation paths ------------------------------------------
    def _check_context_len(self, prompt_ids: list[int]) -> web.Response | None:
        """Reject prompts the KV layout cannot hold with a 400 up front
        (vLLM parity: 'maximum context length' errors) instead of
        admitting the request and streaming an abort."""
        limit = self.config.resolved_max_model_len()
        if len(prompt_ids) >= limit:
            return web.json_response(
                proto.error_json(
                    f"This model's maximum context length is {limit} "
                    f"tokens. However, your request has "
                    f"{len(prompt_ids)} prompt tokens; please reduce "
                    "the length of the messages or prompt.",
                    "context_length_exceeded",
                ),
                status=400,
            )
        return None

    @staticmethod
    def _wants_usage(body: dict) -> bool:
        opts = body.get("stream_options")
        return bool(isinstance(opts, dict) and opts.get("include_usage"))

    # -- logprobs formatting (OpenAI wire shapes) --------------------------
    def _tok_str(self, token_id: int) -> str:
        return self.engine.tokenizer.decode([token_id])

    def _fmt_completion_logprobs(
        self, entries: list[dict] | None, start: int = 0
    ) -> dict | None:
        """Completions shape: tokens / token_logprobs / top_logprobs.
        `start` seeds text_offset — streamed chunks pass the length of
        text already emitted so offsets index the full completion."""
        if entries is None:
            return None
        tokens, lps, tops, offsets = [], [], [], []
        pos = start
        for e in entries:
            s = self._tok_str(e["token_id"])
            tokens.append(s)
            lps.append(e["logprob"])
            top: dict = {}
            for t in e["top_logprobs"]:
                key = self._tok_str(t["token_id"])
                if key in top:
                    # distinct ids can decode to the same string (byte
                    # fallbacks, partial UTF-8): the OpenAI dict shape
                    # would silently drop one — disambiguate with
                    # vLLM's return_tokens_as_token_ids spelling
                    key = f"token_id:{t['token_id']}"
                top[key] = t["logprob"]
            tops.append(top)
            offsets.append(pos)
            pos += len(s)
        return {"tokens": tokens, "token_logprobs": lps,
                "top_logprobs": tops, "text_offset": offsets}

    def _fmt_chat_logprobs(
        self, entries: list[dict] | None
    ) -> dict | None:
        """Chat shape: {"content": [{token, logprob, bytes,
        top_logprobs: [...]}]}."""
        if entries is None:
            return None

        def one(token_id: int, lp: float) -> dict:
            s = self._tok_str(token_id)
            return {"token": s, "logprob": lp,
                    "bytes": list(s.encode("utf-8", "replace"))}

        return {"content": [
            {**one(e["token_id"], e["logprob"]),
             "top_logprobs": [one(t["token_id"], t["logprob"])
                              for t in e["top_logprobs"]]}
            for e in entries
        ]}

    def _stream_chunk(
        self, request_id: str, model: str, chat: bool, text: str,
        new_lps: list[dict] | None, index: int, lp_start: int,
    ) -> tuple[dict, int]:
        """One streamed content chunk (chat or completions) with its
        logprobs attached — the single copy of the chunk wire shape the
        single-choice and multi-choice streams share. Returns
        (chunk, next text_offset seed)."""
        chunk = (
            proto.chat_chunk(
                request_id, model, {"content": text}, None, index=index
            )
            if chat
            else proto.completion_chunk(
                request_id, model, text, None, index=index
            )
        )
        if new_lps:
            if chat:
                chunk["choices"][0]["logprobs"] = (
                    self._fmt_chat_logprobs(new_lps)
                )
            else:
                fmt = self._fmt_completion_logprobs(
                    new_lps, start=lp_start
                )
                chunk["choices"][0]["logprobs"] = fmt
                if fmt["tokens"]:
                    lp_start = (
                        fmt["text_offset"][-1] + len(fmt["tokens"][-1])
                    )
        return chunk, lp_start

    async def _blocking_completion(
        self, request_id: str, sp: SamplingParams, kwargs: dict,
        lora_name: str | None, chat: bool, model: str,
        parse_tools: bool = False, echo_prefix: str | None = None,
    ) -> web.Response:
        arrival = time.time()
        # correlation echo: the response carries the (possibly
        # router-supplied) engine request id so clients/routers join
        # logs, spans, and timelines on one id
        rid_hdr = {REQUEST_ID_HEADER: request_id}
        final = None
        try:
            async for out in self.engine.generate(
                request_id, sampling_params=sp, lora_name=lora_name, **kwargs
            ):
                final = out
        except EngineSleepingError:
            return web.json_response(
                proto.error_json("engine is sleeping", "service_unavailable",
                                 503),
                status=503, headers=rid_hdr,
            )
        except ValueError as e:
            return web.json_response(proto.error_json(str(e)), status=400,
                                     headers=rid_hdr)
        assert final is not None
        self._observe_finish(final, arrival)
        if chat:
            text, tool_calls = final.text, None
            if parse_tools:
                text, tool_calls = tools.parse_tool_calls(final.text)
            resp = proto.chat_response(
                request_id, model, text, final.finish_reason,
                len(final.prompt_token_ids), len(final.token_ids),
                tool_calls=tool_calls,
            )
            resp["choices"][0]["logprobs"] = self._fmt_chat_logprobs(
                final.logprobs
            )
            if final.prompt_logprobs is not None:
                resp["choices"][0]["prompt_logprobs"] = (
                    final.prompt_logprobs
                )
            return web.json_response(resp, headers=rid_hdr)
        resp = proto.completion_response(
            request_id, model,
            (echo_prefix or "") + final.text, final.finish_reason,
            len(final.prompt_token_ids), len(final.token_ids),
        )
        if final.prompt_logprobs is not None:
            # vLLM field: per-prompt-position entries, None first
            resp["choices"][0]["prompt_logprobs"] = final.prompt_logprobs
        resp["choices"][0]["logprobs"] = self._fmt_completion_logprobs(
            final.logprobs
        )
        return web.json_response(resp, headers=rid_hdr)

    async def _multi_completion(
        self, request: web.Request, request_id: str, sp: SamplingParams,
        prompt_ids_list: list[list[int]], lora_name: str | None,
        chat: bool, model: str, stream: bool,
        include_usage: bool = False, parse_tools: bool = False,
        echo_prefixes: list[str] | None = None,
        priority: int = 0,
        traceparent: str | None = None,
    ) -> web.StreamResponse:
        """Batch prompts and/or n>1 sampling: fan the choices out as
        engine sub-requests (continuous batching coalesces them on
        device) and assemble index-ordered choices. Choice index =
        prompt_idx * n + sample_idx (OpenAI/vLLM contract); an explicit
        seed derives per-sample seeds so samples differ but reproduce."""
        import dataclasses

        arrival = time.time()
        rid_hdr = {REQUEST_ID_HEADER: request_id}
        n = sp.n
        plan: list[tuple[int, SamplingParams, list[int]]] = []
        for pi, ids in enumerate(prompt_ids_list):
            for j in range(n):
                sp_j = sp
                if n > 1 and sp.seed is not None:
                    sp_j = dataclasses.replace(sp, seed=sp.seed + j)
                plan.append((pi * n + j, sp_j, ids))

        async def run_one(idx: int, sp_i: SamplingParams,
                          ids: list[int]):
            final = None
            async for out in self.engine.generate(
                f"{request_id}-c{idx}", sampling_params=sp_i,
                lora_name=lora_name, prompt_token_ids=ids,
                priority=priority, traceparent=traceparent,
            ):
                final = out
            return final

        if not stream:
            sub_tasks = [asyncio.ensure_future(run_one(i, s, ids))
                         for i, s, ids in plan]
            try:
                finals = await asyncio.gather(*sub_tasks)
            except BaseException as e:  # noqa: BLE001 — see below
                # ANY failure (or cancellation) must cancel the
                # siblings: their generate() finalizers abort the
                # engine-side requests, so no orphaned generation keeps
                # burning decode steps after the error response
                for t in sub_tasks:
                    if not t.done():
                        t.cancel()
                await asyncio.gather(*sub_tasks, return_exceptions=True)
                if isinstance(e, EngineSleepingError):
                    return web.json_response(
                        proto.error_json("engine is sleeping",
                                         "service_unavailable", 503),
                        status=503, headers=rid_hdr,
                    )
                if isinstance(e, ValueError):
                    return web.json_response(
                        proto.error_json(str(e)), status=400,
                        headers=rid_hdr,
                    )
                if isinstance(e, (asyncio.CancelledError, KeyboardInterrupt,
                                  SystemExit)):
                    raise
                logger.exception("multi-completion failed: %s", e)
                return web.json_response(
                    proto.error_json(f"internal error: {e}",
                                     "internal_error", 500),
                    status=500, headers=rid_hdr,
                )
            choices = []
            for (idx, _, _), final in zip(plan, finals):
                self._observe_finish(final, arrival)
                if chat:
                    text, tool_calls = final.text, None
                    if parse_tools:
                        text, tool_calls = tools.parse_tool_calls(
                            final.text
                        )
                    choice = proto.chat_message_choice(
                        idx, text, final.finish_reason, tool_calls
                    )
                    choice["logprobs"] = self._fmt_chat_logprobs(
                        final.logprobs
                    )
                    if final.prompt_logprobs is not None:
                        choice["prompt_logprobs"] = final.prompt_logprobs
                    choices.append(choice)
                else:
                    pfx = (
                        echo_prefixes[idx // n] if echo_prefixes else ""
                    )
                    choice = {
                        "index": idx, "text": pfx + final.text,
                        "logprobs": self._fmt_completion_logprobs(
                            final.logprobs
                        ),
                        "finish_reason": final.finish_reason,
                    }
                    if final.prompt_logprobs is not None:
                        choice["prompt_logprobs"] = final.prompt_logprobs
                    choices.append(choice)
            return web.json_response(proto.multi_choice_response(
                request_id, model, chat, choices,
                sum(len(ids) for ids in prompt_ids_list),
                sum(len(f.token_ids) for f in finals),
            ), headers=rid_hdr)

        # streamed: interleave per-choice chunks tagged with their index
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                REQUEST_ID_HEADER: request_id,
            },
        )
        await resp.prepare(request)

        async def send(data: dict) -> None:
            await resp.write(
                b"data: " + json.dumps(data).encode() + b"\n\n"
            )

        if echo_prefixes and not chat:
            # OpenAI echo: each choice's stream leads with its prompt
            for idx, _, _ in plan:
                await send(proto.completion_chunk(
                    request_id, model, echo_prefixes[idx // n], None,
                    index=idx,
                ))

        queue: asyncio.Queue = asyncio.Queue()

        async def pump(idx: int, sp_i: SamplingParams, ids: list[int]):
            try:
                final = None
                async for out in self.engine.generate(
                    f"{request_id}-c{idx}", sampling_params=sp_i,
                    lora_name=lora_name, prompt_token_ids=ids,
                    priority=priority, traceparent=traceparent,
                ):
                    final = out
                    if out.delta_text or out.new_logprobs:
                        await queue.put((
                            "delta", idx,
                            (out.delta_text, out.new_logprobs),
                        ))
                await queue.put(("finish", idx, final))
            except Exception as e:  # noqa: BLE001 — surfaced as a chunk
                await queue.put(("error", idx, e))

        tasks = [asyncio.ensure_future(pump(i, s, ids))
                 for i, s, ids in plan]
        completion_tokens = 0
        lp_pos: dict[int, int] = {}  # per-choice text_offset seeds

        async def send_finish(idx: int, reason: str,
                              prompt_lps=None) -> None:
            fin = (
                proto.chat_chunk(request_id, model, {}, reason, index=idx)
                if chat
                else proto.completion_chunk(
                    request_id, model, "", reason, index=idx
                )
            )
            if prompt_lps is not None:
                # same contract as the single-stream path: the field
                # rides the finishing chunk
                fin["choices"][0]["prompt_logprobs"] = prompt_lps
            await send(fin)
        try:
            if chat:
                for idx, _, _ in plan:
                    await send(proto.chat_chunk(
                        request_id, model, {"role": "assistant"}, None,
                        index=idx,
                    ))
            remaining = len(plan)
            while remaining:
                kind, idx, payload = await queue.get()
                if kind == "delta":
                    text, new_lps = payload
                    chunk, lp_pos[idx] = self._stream_chunk(
                        request_id, model, chat, text, new_lps, idx,
                        lp_pos.get(idx, 0),
                    )
                    await send(chunk)
                elif kind == "finish":
                    remaining -= 1
                    if payload is not None:
                        self._observe_finish(payload, arrival)
                        completion_tokens += len(payload.token_ids)
                        await send_finish(idx, payload.finish_reason,
                                          payload.prompt_logprobs)
                else:  # error
                    remaining -= 1
                    await send(proto.error_json(str(payload)))
                    # close the choice so clients waiting on a
                    # finish_reason for every index don't hang
                    await send_finish(idx, "stop")
            if include_usage:
                await send(proto.usage_tail_chunk(
                    request_id, model, chat,
                    sum(len(ids) for ids in prompt_ids_list),
                    completion_tokens,
                ))
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            logger.info("client disconnected from %s", request_id)
            for t in tasks:
                t.cancel()
        await resp.write_eof()
        return resp

    async def _stream_completion(
        self, request: web.Request, request_id: str, sp: SamplingParams,
        kwargs: dict, lora_name: str | None, chat: bool,
        include_usage: bool = False, echo_prefix: str | None = None,
    ) -> web.StreamResponse:
        arrival = time.time()
        model = self.model_name
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                REQUEST_ID_HEADER: request_id,
            },
        )
        await resp.prepare(request)

        async def send(data: dict) -> None:
            await resp.write(
                b"data: " + json.dumps(data).encode() + b"\n\n"
            )

        try:
            if echo_prefix and not chat:
                # OpenAI echo streams the prompt text as the first chunk
                await send(proto.completion_chunk(
                    request_id, model, echo_prefix, None
                ))
            if chat:
                await send(
                    proto.chat_chunk(
                        request_id, model, {"role": "assistant"}, None
                    )
                )
            final = None
            lp_pos = 0
            async for out in self.engine.generate(
                request_id, sampling_params=sp, lora_name=lora_name, **kwargs
            ):
                final = out
                if out.delta_text or out.new_logprobs:
                    chunk, lp_pos = self._stream_chunk(
                        request_id, model, chat, out.delta_text,
                        out.new_logprobs, 0, lp_pos,
                    )
                    await send(chunk)
            if final is not None:
                self._observe_finish(final, arrival)
                if chat:
                    fin = proto.chat_chunk(
                        request_id, model, {}, final.finish_reason
                    )
                    if final.prompt_logprobs is not None:
                        # same contract as completions: the field rides
                        # the finishing chunk
                        fin["choices"][0]["prompt_logprobs"] = (
                            final.prompt_logprobs
                        )
                    await send(fin)
                else:
                    fin = proto.completion_chunk(
                        request_id, model, "", final.finish_reason
                    )
                    if final.prompt_logprobs is not None:
                        # streamed requests get the field on the
                        # finishing chunk (blocking puts it on the
                        # choice) — the engine paid to compute it either
                        # way
                        fin["choices"][0]["prompt_logprobs"] = (
                            final.prompt_logprobs
                        )
                    await send(fin)
                if include_usage:
                    # OpenAI stream_options.include_usage contract: one
                    # final chunk with empty choices + the usage totals
                    await send(proto.usage_tail_chunk(
                        request_id, model, chat,
                        len(final.prompt_token_ids),
                        len(final.token_ids),
                    ))
            await resp.write(b"data: [DONE]\n\n")
        except EngineSleepingError:
            await resp.write(
                b"data: "
                + json.dumps(proto.error_json("engine is sleeping")).encode()
                + b"\n\n"
            )
        except ValueError as e:
            # e.g. duplicate router-supplied x-request-id: the stream is
            # already prepared, so the error rides an SSE chunk
            await resp.write(
                b"data: "
                + json.dumps(proto.error_json(str(e))).encode()
                + b"\n\n"
            )
        except (ConnectionResetError, asyncio.CancelledError):
            logger.info("client disconnected from %s", request_id)
        await resp.write_eof()
        return resp

    # -- embeddings (reference engines serve /v1/embeddings too) -----------
    async def handle_embeddings(self, request: web.Request) -> web.Response:
        body, err = await self._json_body(request)
        if err is not None:
            return err
        err = self._check_model(body)
        if err is not None:
            return err
        model = body.get("model", self.model_name)
        lora_name = model if model in self.lora_adapters else None
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        if (
            not isinstance(inputs, list)
            or not inputs
            or not all(isinstance(x, str) for x in inputs)
        ):
            return web.json_response(
                proto.error_json("'input' must be a non-empty string or "
                                 "list of strings"), status=400
            )

        loop = asyncio.get_running_loop()
        try:
            vecs, n_tokens = await loop.run_in_executor(
                None, self._embed_texts, inputs, lora_name
            )
        except ValueError as e:
            return web.json_response(proto.error_json(str(e)), status=400)
        data = [
            {"object": "embedding", "index": i, "embedding": v.tolist()}
            for i, v in enumerate(vecs)
        ]
        return web.json_response({
            "object": "list",
            "model": model,
            "data": data,
            "usage": {"prompt_tokens": n_tokens,
                      "total_tokens": n_tokens},
        })

    # -- rerank / score (router proxies these; reference engines serve
    # them for reranker/scorer models via cross-encoders. A decoder
    # engine scores by embedding-space cosine — the same decoder-as-
    # embedder pooling /v1/embeddings uses — which preserves the API
    # contract and ordering semantics; plug a cross-encoder family in
    # for calibrated absolute scores.) --------------------------------
    def _embed_texts(self, texts: list[str], lora_name):
        """One text per lock acquisition: an in-flight decode batch only
        ever waits for ONE embedding forward (or its first-bucket
        compile), never the whole list. Shared by /v1/embeddings,
        /v1/rerank, and /v1/score."""
        import numpy as np

        vecs = []
        n_tokens = 0
        for t in texts:
            with self.engine._lock:
                vec, count = self.engine.engine.embed_one(t, lora_name)
            vecs.append(np.asarray(vec))
            n_tokens += count
        return vecs, n_tokens

    async def handle_rerank(self, request: web.Request) -> web.Response:
        """Jina/Cohere-style rerank: query + documents -> sorted scores."""
        body, err = await self._json_body(request)
        if err is not None:
            return err
        err = self._check_model(body)
        if err is not None:
            return err
        query = body.get("query")
        docs = body.get("documents")
        if not isinstance(query, str) or not isinstance(docs, list) or (
            not docs
        ) or not all(isinstance(d, str) for d in docs):
            return web.json_response(
                proto.error_json("'query' must be a string and "
                                 "'documents' a non-empty list of "
                                 "strings"), status=400
            )
        model = body.get("model", self.model_name)
        lora_name = model if model in self.lora_adapters else None
        top_n = body.get("top_n", len(docs))
        if isinstance(top_n, bool) or not isinstance(top_n, int) \
                or top_n < 0:
            return web.json_response(
                proto.error_json("'top_n' must be a non-negative integer"),
                status=400,
            )

        loop = asyncio.get_running_loop()
        try:
            vecs, n_tokens = await loop.run_in_executor(
                None, self._embed_texts, [query] + docs, lora_name
            )
        except ValueError as e:
            return web.json_response(proto.error_json(str(e)), status=400)
        q = vecs[0]
        scored = sorted(
            (
                {"index": i, "relevance_score": float(q @ v),
                 "document": {"text": docs[i]}}
                for i, v in enumerate(vecs[1:])
            ),
            key=lambda r: -r["relevance_score"],
        )[:top_n]
        return web.json_response({
            "id": proto.make_id("rerank"),
            "model": model,
            "results": scored,
            "usage": {"total_tokens": n_tokens},
        })

    async def handle_score(self, request: web.Request) -> web.Response:
        """vLLM-style /v1/score: text_1 x text_2 similarity scores."""
        body, err = await self._json_body(request)
        if err is not None:
            return err
        err = self._check_model(body)
        if err is not None:
            return err
        t1 = body.get("text_1")
        t2 = body.get("text_2")
        if isinstance(t1, str):
            t1 = [t1]
        if isinstance(t2, str):
            t2 = [t2]
        ok = (
            isinstance(t1, list) and isinstance(t2, list) and t1 and t2
            and all(isinstance(x, str) for x in t1 + t2)
            and (len(t1) == 1 or len(t2) == 1 or len(t1) == len(t2))
        )
        if not ok:
            return web.json_response(
                proto.error_json(
                    "'text_1'/'text_2' must be strings or lists of "
                    "strings with broadcastable lengths (1xM, Nx1, NxN)"
                ),
                status=400,
            )
        if len(t1) == 1:
            pairs = [(t1[0], x) for x in t2]
        elif len(t2) == 1:
            pairs = [(x, t2[0]) for x in t1]
        else:
            pairs = list(zip(t1, t2))
        model = body.get("model", self.model_name)
        lora_name = model if model in self.lora_adapters else None
        loop = asyncio.get_running_loop()
        uniq = list(dict.fromkeys(t for p in pairs for t in p))
        try:
            vecs, n_tokens = await loop.run_in_executor(
                None, self._embed_texts, uniq, lora_name
            )
        except ValueError as e:
            return web.json_response(proto.error_json(str(e)), status=400)
        by_text = dict(zip(uniq, vecs))
        data = [
            {"object": "score", "index": i,
             "score": float(by_text[a] @ by_text[b])}
            for i, (a, b) in enumerate(pairs)
        ]
        return web.json_response({
            "id": proto.make_id("score"),
            "object": "list",
            "model": model,
            "data": data,
            "usage": {"total_tokens": n_tokens},
        })

    # -- misc endpoints ----------------------------------------------------
    async def handle_models(self, request: web.Request) -> web.Response:
        cards = [proto.model_card(
            self.model_name,
            kv_instance_id=self.config.kv_instance_id,
            kv_role=self.config.pd_role(),
            max_model_len=self.config.resolved_max_model_len(),
            sp_size=(
                self.config.context_parallel_size
                if getattr(self.engine, "long_prefill", None) is not None
                else None
            ),
        )]
        cards += [
            proto.model_card(name, root=path)
            for name, path in self.lora_adapters.items()
        ]
        return web.json_response({"object": "list", "data": cards})

    async def handle_tokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        if "prompt" in body:
            text = body["prompt"]
        elif "messages" in body:
            text = self.engine.tokenizer.apply_chat_template(body["messages"])
        else:
            return web.json_response(
                proto.error_json("missing 'prompt' or 'messages'"), status=400
            )
        ids = self.engine.tokenizer.encode(text)
        return web.json_response(
            {"tokens": ids, "count": len(ids),
             "max_model_len": self.config.resolved_max_model_len()}
        )

    async def handle_detokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        tokens = body.get("tokens")
        if tokens is None:
            return web.json_response(
                proto.error_json("missing 'tokens'"), status=400
            )
        return web.json_response(
            {"prompt": self.engine.tokenizer.decode(tokens)}
        )

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy"})

    async def handle_version(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"version": production_stack_tpu.__version__}
        )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        self.metrics.update_from_snapshot(self.engine.stats())
        self.metrics.observe_kv(*self.engine.drain_kv_observations())
        self.metrics.observe_decode_k(
            self.engine.drain_decode_k_observations()
        )
        self.metrics.observe_ragged(
            self.engine.drain_ragged_observations()
        )
        return web.Response(
            body=generate_latest(self.registry),
            content_type="text/plain",
            charset="utf-8",
        )

    async def handle_debug_requests(
        self, request: web.Request
    ) -> web.Response:
        """Recent request lifecycle timelines (bounded ring) + in-flight
        ones: enqueue -> admit -> prefill chunks (staged/chained flags)
        -> first token -> sampled decode rounds -> preempt/resume ->
        finish. ?limit=N caps the finished-timeline count."""
        from production_stack_tpu.tracing import debug_requests_payload

        recorder = self.engine.timeline
        return web.json_response(debug_requests_payload(
            request.query.get("limit"),
            enabled=recorder.enabled,
            snapshot=lambda n: recorder.snapshot(limit=n),
            hint="start the engine with request_timeline=True (drop "
                 "--no-request-timeline) to record per-request "
                 "lifecycle timelines",
        ))

    # -- sleep/wake (reference: service_discovery.py:414-441 probes these) -
    async def handle_sleep(self, request: web.Request) -> web.Response:
        level = int(request.query.get("level", "1"))
        self.engine.sleep(level)
        return web.json_response({"status": "sleeping", "level": level})

    async def handle_wake(self, request: web.Request) -> web.Response:
        self.engine.wake_up()
        return web.json_response({"status": "awake"})

    async def handle_is_sleeping(self, request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": self.engine.is_sleeping()})

    # -- LoRA hot-load (reference: loraadapter_controller.go:582-598 POSTs) -
    async def handle_load_lora(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        path = body.get("lora_path")
        if not name or not path:
            return web.json_response(
                proto.error_json("need lora_name and lora_path"), status=400
            )
        try:
            with self.engine._lock:
                self.engine.engine.load_lora(name, path)
        except Exception as e:
            return web.json_response(
                proto.error_json(f"failed to load adapter: {e}", code=500),
                status=500,
            )
        self.lora_adapters[name] = path
        logger.info("loaded LoRA adapter %s from %s", name, path)
        return web.json_response({"status": "success"})

    async def handle_unload_lora(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if name not in self.lora_adapters:
            return web.json_response(
                proto.error_json(f"adapter {name!r} not loaded", code=404),
                status=404,
            )
        with self.engine._lock:
            self.engine.engine.unload_lora(name)
        del self.lora_adapters[name]
        return web.json_response({"status": "success"})

    # -- run ---------------------------------------------------------------
    def run(self, host: str = "0.0.0.0", port: int = 8000) -> None:
        logger.info(
            "engine server for %s listening on %s:%d",
            self.model_name, host, port,
        )
        web.run_app(self.app, host=host, port=port, print=None)
