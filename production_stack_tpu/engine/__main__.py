"""CLI: `python -m production_stack_tpu.engine` — serve a model.

Flag names mirror `vllm serve` where the capability matches (the reference's
helm chart builds exactly these flags, reference:
helm/templates/deployment-vllm-multi.yaml:104-181), so existing deployment
configs translate mechanically.
"""

from __future__ import annotations

import argparse
import os

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.server import EngineServer


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pst-engine", description="TPU-native LLM serving engine"
    )
    p.add_argument("--model", default="pst-tiny-debug",
                   help="preset name or local HF checkpoint dir")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer dir, or 'byte' for the hermetic tokenizer")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--kv-cache-dtype", default="bfloat16")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--block-size", type=int, default=32)
    p.add_argument("--num-kv-blocks", type=int, default=None)
    p.add_argument("--gpu-memory-utilization", "--hbm-utilization",
                   dest="hbm_utilization", type=float, default=0.9)
    p.add_argument("--max-model-len", type=int, default=None)
    p.add_argument("--max-num-seqs", type=int, default=8)
    p.add_argument("--max-num-batched-tokens", "--max-prefill-chunk",
                   dest="max_prefill_chunk", type=int, default=512)
    p.add_argument("--enable-chunked-prefill", action="store_true",
                   default=True)
    p.add_argument("--no-enable-chunked-prefill",
                   dest="enable_chunked_prefill", action="store_false")
    p.add_argument("--max-prefill-seqs", type=int, default=8,
                   help="cross-sequence prefill packing: chunks from up "
                        "to this many sequences share one dispatch "
                        "(1 = no packing)")
    p.add_argument("--scheduling-policy", default="fcfs",
                   choices=["fcfs", "priority"],
                   help="priority: requests carry an integer 'priority' "
                        "(lower = served first); preemption evicts the "
                        "lowest-priority victim")
    p.add_argument("--decode-interleave", type=int, default=1,
                   help="max consecutive prefill chunks while decodes "
                        "wait (0 = prefill always wins)")
    p.add_argument("--num-scheduler-steps", type=int, default=1,
                   help="fused decode+sample iterations per dispatch "
                        "(on-device sampling; amortises host RTT); the "
                        "CAP under --adaptive-decode-k")
    p.add_argument("--device-stop", action="store_true", default=True,
                   help="evaluate EOS/stop-token/max-token stops INSIDE "
                        "the fused decode scan: finished lanes freeze "
                        "mid-round, the host takes exactly the "
                        "generated tokens")
    p.add_argument("--no-device-stop", dest="device_stop",
                   action="store_false",
                   help="fixed-trip fused scan; overshoot discarded on "
                        "the host (chip-window A/B control)")
    p.add_argument("--adaptive-decode-k", action="store_true",
                   default=True,
                   help="size each fused round from pow2 buckets up to "
                        "--num-scheduler-steps: clamped low while "
                        "prefill work waits, bounded by the batch's "
                        "remaining-token budget")
    p.add_argument("--no-adaptive-decode-k", dest="adaptive_decode_k",
                   action="store_false",
                   help="every round dispatches the full "
                        "--num-scheduler-steps (fixed-K control)")
    p.add_argument("--num-speculative-tokens", type=int, default=0,
                   help="ngram prompt-lookup speculative decoding: "
                        "draft up to this many tokens and verify them "
                        "in one forward (greedy batch-1 decode; 0=off)")
    p.add_argument("--ngram-prompt-lookup-max", type=int, default=3)
    p.add_argument("--ngram-prompt-lookup-min", type=int, default=1)
    p.add_argument("--async-decode", action="store_true", default=False,
                   help="double-buffered decode: dispatch round N+1 on "
                        "round N's on-device tokens before fetching it "
                        "(measured slower than the default synchronous "
                        "path with --prefetch-decode at K=8; see PERF.md)")
    p.add_argument("--no-async-decode", dest="async_decode",
                   action="store_false")
    p.add_argument("--prefetch-decode", action="store_true", default=True,
                   help="speculative h2d prefetch: upload the next fused "
                        "round's inputs while the current one executes")
    p.add_argument("--no-prefetch-decode", dest="prefetch_decode",
                   action="store_false")
    p.add_argument("--prefill-pipeline", action="store_true",
                   default=True,
                   help="pipelined prefill: one fused h2d buffer per "
                        "prefill dispatch, chunk N+1 staged while chunk "
                        "N computes, cold multi-chunk prompts chained "
                        "without host round-trips")
    p.add_argument("--no-prefill-pipeline", dest="prefill_pipeline",
                   action="store_false",
                   help="serial per-array prefill uploads (the "
                        "pre-pipeline path; bench attribution control)")
    p.add_argument("--ragged-dispatch", action="store_true",
                   default=True,
                   help="unified ragged prefill+decode rounds: when "
                        "prefill chunks and decode lanes are both "
                        "ready, dispatch them as ONE lane-typed device "
                        "program — no prefill/decode interleave wait")
    p.add_argument("--no-ragged-dispatch", dest="ragged_dispatch",
                   action="store_false",
                   help="split alternating prefill/decode rounds (the "
                        "pre-ragged path; bench attribution control)")
    p.add_argument("--ragged-kernel", action="store_true",
                   default=True,
                   help="single-kernel ragged paged attention: ONE "
                        "batched-grid Pallas kernel serves any lane "
                        "mix (decode rows + prefill q-tiles share the "
                        "grid), shrinking the precompile variant "
                        "space to row-count buckets (pallas impl only)")
    p.add_argument("--no-ragged-kernel", dest="ragged_kernel",
                   action="store_false",
                   help="compose per-lane prefill/decode kernels (the "
                        "pre-unified kernels; bench attribution "
                        "control)")
    p.add_argument("--precompile-serving", action="store_true",
                   default=False,
                   help="compile every steady-state prefill/decode "
                        "program shape at startup so no XLA compile "
                        "lands inside a live request (minutes of "
                        "startup the first time; cheap on restart with "
                        "JAX_COMPILATION_CACHE_DIR)")
    p.add_argument("--enable-prefix-caching", action="store_true",
                   default=True)
    p.add_argument("--no-enable-prefix-caching",
                   dest="enable_prefix_caching", action="store_false")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--pipeline-parallel-size", type=int, default=1)
    p.add_argument("--context-parallel-size", type=int, default=0,
                   help="sp mesh axis for the long-prefill ring "
                   "(tp x sp devices; 0 = no ring)")
    p.add_argument("--long-prefill-threshold", type=int, default=None,
                   help="prompts whose uncached remainder exceeds this "
                   "many tokens run as context-parallel ring prefill "
                   "(requires --context-parallel-size > 1)")
    p.add_argument("--long-prefill-chunk", type=int, default=2048,
                   help="ring prefill chunk length in tokens")
    p.add_argument("--enable-lora", action="store_true")
    p.add_argument("--max-loras", type=int, default=4)
    p.add_argument("--enable-sleep-mode", action="store_true",
                   help="advertise sleep/wake support (endpoints always on)")
    p.add_argument("--enable-auto-tool-choice", action="store_true",
                   help="honor OpenAI `tools` with tool_choice=auto "
                        "(engine/tools.py)")
    p.add_argument("--tool-call-parser", default="hermes",
                   choices=["hermes"],
                   help="tool-call output format to parse")
    p.add_argument("--api-key", default=os.environ.get("PST_API_KEY"),
                   help="require `Authorization: Bearer <key>` on /v1/* "
                        "(default: $PST_API_KEY, so k8s can mount the key "
                        "as a Secret env instead of exposing it on argv)")
    p.add_argument("--chat-template", default=None,
                   help="Jinja chat-template override: a template string "
                        "or a path to a template file")
    p.add_argument("--attention-impl", default="auto",
                   choices=["auto", "xla", "pallas"])
    # observability: per-request lifecycle timelines + span export
    p.add_argument("--request-timeline", action="store_true",
                   default=True,
                   help="record per-request lifecycle timelines "
                        "(enqueue/admit/prefill-chunks/first-token/"
                        "decode-rounds/preempt/finish) served by "
                        "/debug/requests")
    p.add_argument("--no-request-timeline", dest="request_timeline",
                   action="store_false",
                   help="disable timeline recording (every hook "
                        "degrades to one boolean check)")
    p.add_argument("--timeline-ring-size", type=int, default=256,
                   help="finished timelines kept for /debug/requests")
    p.add_argument("--tracing-exporter", default="none",
                   choices=["none", "log", "memory", "otlp"],
                   help="engine-side span export: one engine_request "
                        "span per request (child of the router span "
                        "via the propagated traceparent header)")
    # disaggregated prefill / KV transfer
    p.add_argument("--kv-role", default=None,
                   choices=[None, "prefill", "decode", "both",
                            "kv_producer", "kv_consumer"],
                   help="disaggregated prefill/decode role (advertised "
                        "to the router's `pd` policy via /v1/models; "
                        "kv_producer/kv_consumer are vLLM-flag-compat "
                        "aliases for prefill/decode)")
    p.add_argument("--kv-transfer-listen", default=None,
                   help="host:port to serve KV block chains on "
                        "(prefill/both roles)")
    p.add_argument("--kv-peer", default=None,
                   help="comma list of peer addresses to pull KV from "
                        "(decode/both roles): prefill engines' "
                        "--kv-transfer-listen addresses or a "
                        "kv.cache_server, address-interchangeably")
    # KV offload (LMCache-equivalent)
    p.add_argument("--max-lora-rank", type=int, default=16)
    p.add_argument("--cpu-offload-gb", type=float, default=0.0)
    p.add_argument("--disk-offload-dir", default=None)
    p.add_argument("--remote-cache-url", default=None)
    p.add_argument("--kv-controller-url", default=None)
    p.add_argument("--kv-instance-id", default="default-instance")
    p.add_argument("--sync-kv-offload", action="store_true",
                   default=False,
                   help="pre-PR-4 synchronous KV tier traffic: d2h "
                        "export inside scheduling and blocking tier "
                        "reads + whole-cache-copy import on the step "
                        "loop (bench attribution control; the default "
                        "is the zero-stall async export/staged-restore "
                        "path)")
    p.add_argument("--kv-restore-wait-s", type=float, default=2.0,
                   help="staged-restore admission budget: max seconds a "
                        "waiting request may hold its admission slot "
                        "while its KV tier fetch + h2d staging are in "
                        "flight before recomputing from scratch")
    p.add_argument("--multihost", action="store_true",
                   help="one engine spanning a multi-host slice: host 0 "
                        "schedules + serves HTTP, other hosts replay its "
                        "steps (jax.distributed SPMD)")
    p.add_argument("--coordinator-address", default=None,
                   help="host0:port for jax.distributed (defaults to "
                        "COORDINATOR_ADDRESS env / TPU metadata)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def config_from_args(args: argparse.Namespace) -> EngineConfig:
    # vLLM-flag-compat aliases; prefill/decode/both pass through
    role = {
        "kv_producer": "prefill", "kv_consumer": "decode",
    }.get(args.kv_role, args.kv_role)
    return EngineConfig(
        model=args.model,
        tokenizer=args.tokenizer,
        chat_template=args.chat_template,
        dtype=args.dtype,
        cache_dtype=args.kv_cache_dtype,
        seed=args.seed,
        block_size=args.block_size,
        num_kv_blocks=args.num_kv_blocks,
        hbm_utilization=args.hbm_utilization,
        max_model_len=args.max_model_len,
        max_num_seqs=args.max_num_seqs,
        scheduling_policy=args.scheduling_policy,
        max_prefill_chunk=args.max_prefill_chunk,
        enable_chunked_prefill=args.enable_chunked_prefill,
        max_prefill_seqs=args.max_prefill_seqs,
        decode_interleave=args.decode_interleave,
        num_scheduler_steps=args.num_scheduler_steps,
        device_stop=args.device_stop,
        adaptive_decode_k=args.adaptive_decode_k,
        async_decode=args.async_decode,
        precompile_serving=args.precompile_serving,
        prefetch_decode=args.prefetch_decode,
        prefill_pipeline=args.prefill_pipeline,
        ragged_dispatch=args.ragged_dispatch,
        ragged_kernel=args.ragged_kernel,
        num_speculative_tokens=args.num_speculative_tokens,
        ngram_prompt_lookup_max=args.ngram_prompt_lookup_max,
        ngram_prompt_lookup_min=args.ngram_prompt_lookup_min,
        enable_prefix_caching=args.enable_prefix_caching,
        tensor_parallel_size=args.tensor_parallel_size,
        pipeline_parallel_size=args.pipeline_parallel_size,
        context_parallel_size=args.context_parallel_size,
        long_prefill_threshold=args.long_prefill_threshold,
        long_prefill_chunk=args.long_prefill_chunk,
        multihost=args.multihost,
        served_model_name=args.served_model_name,
        enable_lora=args.enable_lora,
        max_loras=args.max_loras,
        max_lora_rank=args.max_lora_rank,
        enable_auto_tool_choice=args.enable_auto_tool_choice,
        tool_call_parser=args.tool_call_parser,
        api_key=args.api_key,
        attention_impl=args.attention_impl,
        request_timeline=args.request_timeline,
        timeline_ring_size=args.timeline_ring_size,
        tracing_exporter=args.tracing_exporter,
        kv_role=role,
        kv_transfer_config={
            "listen": args.kv_transfer_listen,
            "peer": args.kv_peer,
        },
        cpu_offload_bytes=int(args.cpu_offload_gb * 2**30),
        disk_offload_dir=args.disk_offload_dir,
        remote_cache_url=args.remote_cache_url,
        kv_controller_url=args.kv_controller_url,
        kv_instance_id=args.kv_instance_id,
        sync_kv_offload=args.sync_kv_offload,
        kv_restore_wait_s=args.kv_restore_wait_s,
    )


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    # chip-session hygiene: refuse to start a second process that would
    # dial the real TPU (a second dial hangs in backend init and can
    # wedge a remote-attached chip); SIGTERM is the sanctioned stop
    from production_stack_tpu.utils import chip_guard

    _chip_lock = chip_guard.engage()  # noqa: F841 — held for process life
    if args.kv_instance_id == "default-instance":
        # by convention the instance id is host:port so kvaware routing can
        # map controller matches back to endpoint urls (routing_logic.py);
        # 0.0.0.0 never appears in an endpoint url, so resolve a real
        # address for the id
        host = args.host
        if host in ("0.0.0.0", "::", ""):
            import socket

            try:
                host = socket.gethostbyname(socket.gethostname())
            except OSError:
                host = "127.0.0.1"
        args.kv_instance_id = f"{host}:{args.port}"
    if args.multihost:
        # must run before anything touches a device (jax.distributed)
        from production_stack_tpu.parallel import multihost

        multihost.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        if multihost.process_index() != 0:
            # follower host: no HTTP server, replay host 0's device steps
            from production_stack_tpu.engine.model_runner import ModelRunner
            from production_stack_tpu.engine.multihost_engine import (
                follower_loop,
                validate_multihost_config,
            )

            cfg = config_from_args(args)
            validate_multihost_config(cfg)
            follower_loop(ModelRunner(cfg))
            return
    server = EngineServer(config_from_args(args))
    server.run(host=args.host, port=args.port)


if __name__ == "__main__":
    main()
