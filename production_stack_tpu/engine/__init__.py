"""TPU-native serving engine: continuous batching over a paged KV cache."""
