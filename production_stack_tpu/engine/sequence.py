"""Request/sequence state tracked by the scheduler."""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field

import xxhash

from production_stack_tpu.engine.sampling_params import SamplingParams


class SequenceStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED_STOPPED = "stop"
    FINISHED_LENGTH = "length"
    FINISHED_ABORTED = "abort"

    @property
    def finished(self) -> bool:
        return self in (
            SequenceStatus.FINISHED_STOPPED,
            SequenceStatus.FINISHED_LENGTH,
            SequenceStatus.FINISHED_ABORTED,
        )


@dataclass
class RequestMetrics:
    arrival_time: float = field(default_factory=time.time)
    # first admission WAITING -> RUNNING (queue-wait = admitted - arrival)
    admitted_time: float | None = None
    first_scheduled_time: float | None = None
    first_token_time: float | None = None
    finished_time: float | None = None
    num_cached_prompt_tokens: int = 0
    num_preemptions: int = 0
    # wall seconds spent preempted (preempt -> re-admission), summed over
    # every preemption; feeds tpu:preemption_stall_seconds
    preempt_stall_s: float = 0.0
    last_preempt_time: float | None = None


class Sequence:
    """One request's sequence (n=1; parallel sampling fans out to n Sequences)."""

    _arrival_counter = 0

    def __init__(
        self,
        request_id: str,
        prompt_token_ids: list[int],
        sampling_params: SamplingParams,
        eos_token_id: int | None,
        arrival_time: float | None = None,
        lora_name: str | None = None,
        hash_seed: int | None = None,
        priority: int = 0,
    ):
        self.request_id = request_id
        self.prompt_token_ids = list(prompt_token_ids)
        # preemption-by-recompute folds generated tokens into the prompt;
        # orig_prompt_len keeps the user-visible prompt/output boundary
        self.orig_prompt_len = len(self.prompt_token_ids)
        self.output_token_ids: list[int] = []
        self.sampling_params = sampling_params
        self.eos_token_id = eos_token_id
        self.lora_name = lora_name
        # prefix-cache hash-chain seed: LoRA requests must never share KV
        # blocks with base-model (or other-adapter) requests, so the chain
        # starts from a per-adapter seed instead of 0 (the engine passes a
        # LoraManager-derived seed that also folds in the load generation)
        if hash_seed is not None:
            self.hash_seed = hash_seed
        elif lora_name is None:
            self.hash_seed = 0
        else:
            self.hash_seed = xxhash.xxh64(
                b"lora:" + lora_name.encode()
            ).intdigest()
        # vLLM --scheduling-policy priority role: LOWER value = served
        # first; ties break by arrival order (a per-process ordinal, not
        # wall time, so equal-timestamp arrivals stay FIFO)
        self.priority = priority
        Sequence._arrival_counter += 1
        self.arrival_ordinal = Sequence._arrival_counter
        self.status = SequenceStatus.WAITING
        self.metrics = RequestMetrics()
        if arrival_time is not None:
            self.metrics.arrival_time = arrival_time

        # paged-KV state (owned by the block manager)
        self.block_table: list[int] = []
        # tokens whose K/V are already in the cache (prefix-cache hits count)
        self.num_computed_tokens = 0
        # long-prefill lane (engine/long_prefill.py): True while the
        # context-parallel ring computes this prompt — the scheduler's
        # chunked-prefill planners skip the sequence and the engine
        # drives its ring chunks + KV landing outside schedule()
        self.long_prefill_active = False

        # incremental prefix-cache hashing state (chain hashes of the
        # sequence's full blocks registered so far)
        self.block_hashes: list[int] = []

        # detokenization state
        self.output_text = ""
        self._stopped_by: str | None = None

    # -- lengths ----------------------------------------------------------
    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> list[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def generated_token_ids(self) -> list[int]:
        """All tokens generated for this request, including any folded into
        the prompt by preemption-recompute."""
        return self.prompt_token_ids[self.orig_prompt_len :] + (
            self.output_token_ids
        )

    @property
    def prefill_done(self) -> bool:
        """All prompt tokens have K/V in cache and first logits were produced."""
        return self.num_computed_tokens >= self.num_prompt_tokens

    @property
    def num_uncomputed_prompt_tokens(self) -> int:
        return max(0, self.num_prompt_tokens - self.num_computed_tokens)

    @property
    def finished(self) -> bool:
        return self.status.finished

    @property
    def finish_reason(self) -> str | None:
        if not self.status.finished:
            return None
        return self.status.value

    def append_token(self, token_id: int) -> None:
        """Append a sampled token. Its K/V is computed by the decode step
        that later consumes it, so num_computed_tokens is NOT advanced here
        (invariant during decode: num_computed_tokens == num_tokens - 1)."""
        self.output_token_ids.append(token_id)

    def check_stop(self, new_text: str | None = None) -> None:
        """Update status if a stop condition fired on the latest token."""
        sp = self.sampling_params
        n_generated = len(self.generated_token_ids)
        if n_generated >= sp.max_tokens:
            self.status = SequenceStatus.FINISHED_LENGTH
            return
        if n_generated < sp.min_tokens:
            return
        last = self.output_token_ids[-1]
        if not sp.ignore_eos and self.eos_token_id is not None:
            if last == self.eos_token_id:
                self.status = SequenceStatus.FINISHED_STOPPED
                return
        if last in sp.stop_token_ids:
            self.status = SequenceStatus.FINISHED_STOPPED
            return
        if sp.stop and new_text is not None:
            for s in sp.stop:
                idx = self.output_text.find(s)
                if idx != -1:
                    # vLLM include_stop_str_in_output: keep the matched
                    # stop string (truncate AFTER it, not before)
                    end = idx + (len(s) if sp.include_stop_str_in_output
                                 else 0)
                    self.output_text = self.output_text[:end]
                    self._stopped_by = s
                    self.status = SequenceStatus.FINISHED_STOPPED
                    return

    def reset_for_recompute(self) -> None:
        """Preemption by recomputation: drop cache state, keep tokens.

        Generated tokens are folded into the prompt so the whole sequence is
        re-prefilled on resumption (same trick vLLM uses for recompute).
        """
        self.prompt_token_ids = self.all_token_ids
        self.output_token_ids = []
        # keep output_text; new tokens will continue appending
        self.num_computed_tokens = 0
        self.block_table = []
        self.block_hashes = []
        self.long_prefill_active = False
        self.status = SequenceStatus.PREEMPTED
        self.metrics.num_preemptions += 1
        self.metrics.last_preempt_time = time.time()
