"""One engine spanning the hosts of a multi-host TPU slice.

TPU-native replacement for the reference's Ray pipeline-parallel
multi-host path (reference: helm/templates/ray-cluster.yaml:1-622,
tutorial 15 `pipelineParallelSize`): instead of a Ray actor tree, the
engine runs SPMD under jax.distributed — every host executes the same
jitted steps over a global (tp) mesh whose devices span the slice, and
XLA lays the collectives on ICI/DCN.

Control flow: the scheduler, HTTP server, and sampler live on host 0
only. Host 0 wraps its ModelRunner in `BroadcastingRunner`, which
publishes a step descriptor (step kind + host-side integer args) through
the jax.distributed coordinator KV store before executing it locally;
follower hosts run `follower_loop`, replaying each descriptor against
their local ModelRunner so all hosts issue identical device programs in
identical order (the SPMD contract).

Scope (documented, loudly enforced in config validation below):
base-model serving, /v1/embeddings, and speculative decoding (embed and
verify_batch steps broadcast like decode) — KV offload tiers, PD
transfer, and LoRA hot-load remain single-host features for now (each
needs its own broadcast/addressability story).
"""

from __future__ import annotations

import numpy as np

from production_stack_tpu.parallel import multihost
from production_stack_tpu.utils import init_logger

logger = init_logger(__name__)


def _b64(a) -> dict:
    """ndarray -> JSON-safe {b64, shape, dt}: the step broadcast rides
    the jax.distributed coordinator KV store as JSON, and raw-bytes
    base64 beats a Python-int list by ~10x in size and parse cost for
    the big guided tables."""
    import base64

    a = np.ascontiguousarray(a)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "shape": list(a.shape), "dt": str(a.dtype)}


def _unb64(d: dict):
    import base64

    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dt"])
    ).reshape(d["shape"])


def validate_multihost_config(config) -> None:
    """Reject single-host-only features early with a clear message."""
    problems = []
    if config.enable_lora:
        problems.append("--enable-lora (adapter loads are not broadcast)")
    if config.cpu_offload_bytes or config.disk_offload_dir or (
        config.remote_cache_url
    ):
        problems.append(
            "KV offload tiers (cache export needs host-0-addressable "
            "shards)"
        )
    if config.kv_role:
        problems.append("disaggregated prefill roles")
    if problems:
        raise ValueError(
            "multihost mode does not yet support: " + "; ".join(problems)
        )


class BroadcastingRunner:
    """Host-0 ModelRunner proxy: publish each device step, then run it.

    Only the methods that issue device programs are intercepted; all
    other attribute access (model_config, num_blocks, params, ...)
    delegates to the wrapped runner.
    """

    def __init__(self, runner, broadcaster: multihost.StepBroadcaster):
        self._runner = runner
        self._bc = broadcaster

    def __getattr__(self, name):
        return getattr(self._runner, name)

    @staticmethod
    def _sampling_msg(sampling):
        if sampling is None:
            return None
        temps, top_ps, top_ks, min_ps, keys = sampling
        return [
            np.asarray(temps, np.float32).tolist(),
            np.asarray(top_ps, np.float32).tolist(),
            np.asarray(top_ks, np.int32).tolist(),
            np.asarray(min_ps, np.float32).tolist(),
            np.asarray(keys, np.uint32).tolist(),
        ]

    def prefill(self, token_ids, start_pos, block_table, total_len,
                lora_slot=0, sampling=None, prompt_lp_targets=None):
        self._bc.publish({
            "kind": "prefill",
            "token_ids": [int(t) for t in token_ids],
            "start_pos": int(start_pos),
            "block_table": [int(b) for b in block_table],
            "total_len": int(total_len),
            "lora_slot": int(lora_slot),
            "sampling": self._sampling_msg(sampling),
            # followers must select the SAME program variant (the plp
            # prefill materializes every row) or SPMD desyncs
            "prompt_lp_targets": (
                [int(t) for t in prompt_lp_targets]
                if prompt_lp_targets is not None else None
            ),
        })
        return self._runner.prefill(
            token_ids, start_pos, block_table, total_len,
            lora_slot=lora_slot, sampling=sampling,
            prompt_lp_targets=prompt_lp_targets,
        )

    def prefill_batch(self, chunks, start_positions, block_tables,
                      total_lens, lora_slots=None, sampling=None):
        msg = {
            "kind": "prefill_batch",
            "chunks": [[int(t) for t in c] for c in chunks],
            "start_positions": [int(p) for p in start_positions],
            "block_tables": [[int(b) for b in t] for t in block_tables],
            "total_lens": [int(t) for t in total_lens],
            "sampling": self._sampling_msg(sampling),
        }
        if lora_slots is not None:
            msg["lora_slots"] = [int(s) for s in lora_slots]
        self._bc.publish(msg)
        return self._runner.prefill_batch(
            chunks, start_positions, block_tables, total_lens,
            lora_slots=lora_slots, sampling=sampling,
        )

    def decode(self, token_ids, positions, block_tables, context_lens,
               lora_slots=None):
        msg = {
            "kind": "decode",
            "token_ids": [int(t) for t in token_ids],
            "positions": [int(p) for p in positions],
            "block_tables": [[int(b) for b in t] for t in block_tables],
            "context_lens": [int(c) for c in context_lens],
        }
        if lora_slots is not None:
            msg["lora_slots"] = [int(s) for s in lora_slots]
        self._bc.publish(msg)
        return self._runner.decode(
            token_ids, positions, block_tables, context_lens,
            lora_slots=lora_slots,
        )

    def decode_multi(self, token_ids, positions, block_tables,
                     context_lens, steps, temps, top_ps, top_ks, keys,
                     min_ps=None, lora_slots=None, penalties=None,
                     want_logprobs=False, guided=None, logit_bias=None):
        msg = {
            "kind": "decode_multi",
            "token_ids": [int(t) for t in token_ids],
            "positions": [int(p) for p in positions],
            "block_tables": [[int(b) for b in t] for t in block_tables],
            "context_lens": [int(c) for c in context_lens],
            "steps": int(steps),
            "temps": np.asarray(temps).tolist(),
            "top_ps": np.asarray(top_ps).tolist(),
            "top_ks": np.asarray(top_ks).tolist(),
            "min_ps": (
                np.asarray(min_ps, np.float32).tolist()
                if min_ps is not None else None
            ),
            "keys": np.asarray(keys, np.uint32).tolist(),
            # followers must compile the SAME program variant as host 0
            # (the logprobs scan has extra outputs) or SPMD desyncs
            "want_logprobs": bool(want_logprobs),
        }
        if lora_slots is not None:
            msg["lora_slots"] = [int(s) for s in lora_slots]
        if penalties is not None:
            gen, pres, freq, rep = penalties
            msg["penalties"] = {
                "gen": [[int(t) for t in g] for g in gen],
                "pres": np.asarray(pres).tolist(),
                "freq": np.asarray(freq).tolist(),
                "rep": np.asarray(rep).tolist(),
            }
        if logit_bias is not None:
            msg["logit_bias"] = {
                "ids": np.asarray(logit_bias[0], np.int32).tolist(),
                "vals": np.asarray(logit_bias[1], np.float32).tolist(),
            }
        if guided is not None:
            tok, init_states, lane_map, tc, cm, ct = guided
            # cache_token serials are process-local; serialize as a
            # list so every follower re-keys its device cache
            # consistently. The BIG tables ride the broadcast only when
            # the constraint set CHANGES — per-dispatch they are
            # device-cached on every host, so steady-state guided
            # decode adds just the (b,) init/lane vectors to the wire.
            wire_tok = list(map(int, tok[0])) + list(tok[1:])
            msg["guided"] = {
                "token": wire_tok,
                "init": np.asarray(init_states).tolist(),
                "lane": np.asarray(lane_map).tolist(),
            }
            if getattr(self, "_guided_sent_token", None) != tuple(
                wire_tok
            ):
                # raw int32/int8 bytes via base64, NOT a JSON int list:
                # tc is (m_pad, vocab) — with a 128k vocab a tolist()
                # payload is several MB of Python ints to serialize and
                # for every follower to parse. Pad rows (all-zero, above
                # n_real) are rebuilt follower-side, not shipped.
                n_real = len(tok[0]) + 1
                msg["guided"]["tc"] = _b64(np.asarray(tc)[:n_real])
                msg["guided"]["cm"] = _b64(
                    np.asarray(cm).astype(np.int8)
                )
                msg["guided"]["ct"] = _b64(np.asarray(ct))
                self._guided_sent_token = tuple(wire_tok)
        self._bc.publish(msg)
        return self._runner.decode_multi(
            token_ids, positions, block_tables, context_lens, steps,
            temps, top_ps, top_ks, keys, min_ps=min_ps,
            lora_slots=lora_slots, penalties=penalties,
            want_logprobs=want_logprobs, guided=guided,
            logit_bias=logit_bias,
        )

    def verify_batch(self, chunks, start_positions, block_tables,
                     total_lens, row_sampling, lora_slots=None):
        temps, top_ps, top_ks, min_ps, seeds, starts = row_sampling
        msg = {
            "kind": "verify_batch",
            "chunks": [[int(t) for t in c] for c in chunks],
            "start_positions": [int(p) for p in start_positions],
            "block_tables": [[int(b) for b in t] for t in block_tables],
            "total_lens": [int(t) for t in total_lens],
            "row_sampling": [
                np.asarray(temps, np.float32).tolist(),
                np.asarray(top_ps, np.float32).tolist(),
                np.asarray(top_ks, np.int32).tolist(),
                np.asarray(min_ps, np.float32).tolist(),
                np.asarray(seeds, np.uint32).tolist(),
                np.asarray(starts, np.int64).tolist(),
            ],
        }
        if lora_slots is not None:
            msg["lora_slots"] = [int(s) for s in lora_slots]
        self._bc.publish(msg)
        return self._runner.verify_batch(
            chunks, start_positions, block_tables, total_lens,
            row_sampling=row_sampling, lora_slots=lora_slots,
        )

    def embed(self, token_ids, lora_slot=0):
        self._bc.publish({
            "kind": "embed",
            "token_ids": [int(t) for t in token_ids],
            "lora_slot": int(lora_slot),
        })
        return self._runner.embed(token_ids, lora_slot=lora_slot)

    def precompile_prefill(self, singles=(), groups=()):
        # broadcast so FOLLOWERS compile ahead too — a follower that
        # first meets a program shape inside a live replayed step stalls
        # the whole collective for the compile
        self._bc.publish({
            "kind": "precompile_prefill",
            "singles": [[int(a), int(b)] for a, b in singles],
            "groups": [[int(s), int(a), int(b)] for s, a, b in groups],
        })
        return self._runner.precompile_prefill(singles, groups)

    def precompile_decode(self, context_lens, steps, chained=False,
                          stop=False):
        # stop is always False under multihost (_device_stop is gated
        # off — the broadcast wire ships host token lists, not stop
        # matrices), but precompile_serving passes the kwarg
        # unconditionally, so the proxy must accept and forward it
        self._bc.publish({
            "kind": "precompile_decode",
            "context_lens": [int(c) for c in context_lens],
            "steps": int(steps),
            "chained": bool(chained),
            "stop": bool(stop),
        })
        return self._runner.precompile_decode(
            context_lens, steps, chained=chained, stop=stop,
        )

    def shutdown_followers(self) -> None:
        self._bc.publish({"kind": "shutdown"})


def wrap_engine_for_multihost(engine) -> None:
    """Host 0: swap the engine's runner for the broadcasting proxy."""
    engine.runner = BroadcastingRunner(
        engine.runner, multihost.StepBroadcaster()
    )
    logger.info(
        "multihost host 0: broadcasting steps to %d follower hosts",
        multihost.process_count() - 1,
    )


def follower_loop(runner, timeout_s: float = 600.0) -> None:
    """Follower hosts: replay host 0's device steps until shutdown."""
    bc = multihost.StepBroadcaster()
    logger.info(
        "multihost follower %d: replaying host 0's steps",
        multihost.process_index(),
    )
    while True:
        msg = bc.next(timeout_s=timeout_s)
        kind = msg.pop("kind")
        if kind == "shutdown":
            logger.info("follower: shutdown received")
            return
        if kind == "prefill":
            runner.prefill(**msg)
        elif kind == "prefill_batch":
            runner.prefill_batch(**msg)
        elif kind == "decode":
            runner.decode(**msg)
        elif kind == "decode_multi":
            for arr in ("temps", "top_ps", "top_ks"):
                msg[arr] = np.asarray(msg[arr], np.float32
                                      if arr != "top_ks" else np.int32)
            if msg.get("min_ps") is not None:
                msg["min_ps"] = np.asarray(msg["min_ps"], np.float32)
            msg["keys"] = np.asarray(msg["keys"], np.uint32)
            lb = msg.pop("logit_bias", None)
            if lb is not None:
                msg["logit_bias"] = (
                    np.asarray(lb["ids"], np.int32),
                    np.asarray(lb["vals"], np.float32),
                )
            pen = msg.pop("penalties", None)
            if pen is not None:
                msg["penalties"] = (
                    pen["gen"],
                    np.asarray(pen["pres"], np.float32),
                    np.asarray(pen["freq"], np.float32),
                    np.asarray(pen["rep"], np.float32),
                )
            gd = msg.pop("guided", None)
            if gd is not None:
                tok = tuple(gd["token"])
                if "tc" in gd:
                    tc = _unb64(gd["tc"])
                    m_pad = tok[-1]  # cache_token layout: (..., m_pad)
                    if tc.shape[0] < m_pad:  # re-grow the all-zero pad
                        tc = np.concatenate([tc, np.zeros(
                            (m_pad - tc.shape[0], tc.shape[1]), np.int32
                        )])
                    tables = (
                        tc,
                        _unb64(gd["cm"]).astype(bool),
                        _unb64(gd["ct"]),
                    )
                    runner._guided_follower_tables = (tok, tables)
                else:
                    # host 0 sends the big tables only when the
                    # constraint set changes; in-order broadcast means
                    # they were seen before
                    cached = getattr(
                        runner, "_guided_follower_tables", None
                    )
                    if cached is None or cached[0] != tok:
                        raise RuntimeError(
                            "guided decode broadcast referenced tables "
                            "this follower never received"
                        )
                    tables = cached[1]
                msg["guided"] = (
                    tok,
                    np.asarray(gd["init"], np.int32),
                    np.asarray(gd["lane"], np.int32),
                    *tables,
                )
            runner.decode_multi(**msg)
        elif kind == "verify_batch":
            rs = msg.pop("row_sampling")
            msg["row_sampling"] = (
                np.asarray(rs[0], np.float32),
                np.asarray(rs[1], np.float32),
                np.asarray(rs[2], np.int32),
                np.asarray(rs[3], np.float32),
                np.asarray(rs[4], np.uint32),
                np.asarray(rs[5], np.int64),
            )
            runner.verify_batch(**msg)
        elif kind == "embed":
            runner.embed(**msg)
        elif kind == "precompile_prefill":
            runner.precompile_prefill(**msg)
        elif kind == "precompile_decode":
            runner.precompile_decode(**msg)
        else:  # future step kinds must fail loudly, not silently desync
            raise RuntimeError(f"unknown multihost step kind {kind!r}")
