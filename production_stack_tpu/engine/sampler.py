"""Batched token sampler, jit-compiled with static shapes.

TPU-first design: instead of a per-request Python loop, sampling is one fused
XLA program over the whole decode batch. Temperature / top-k / top-p are
per-row vectors; randomness is per-row counter-based PRNG keys so results are
reproducible regardless of batch composition.

Top-k/top-p operate within a static TOP_CAP-candidate window (`lax.top_k`),
which avoids a full 128k-vocab sort on the MXU-unfriendly sort path. greedy
rows use the exact full-vocab argmax. TOP_CAP bounds the effective top_k; for
top_p the residual probability mass outside the top-64 of an LLM softmax is
negligible, and vLLM's TPU backend makes the same trade.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

TOP_CAP = 64


@functools.partial(jax.jit, static_argnames=("top_cap",))
def sample_tokens(
    logits: jax.Array,  # (b, vocab) float32
    temperature: jax.Array,  # (b,) float32; 0 => greedy
    top_p: jax.Array,  # (b,) float32 in (0, 1]
    top_k: jax.Array,  # (b,) int32; <=0 => disabled
    key_data: jax.Array,  # (b, 2) uint32 per-row PRNG key data
    min_p: jax.Array | None = None,  # (b,) float32 in [0, 1]; 0 => off
    top_cap: int = TOP_CAP,
) -> jax.Array:
    """Sample one token per row. Returns (b,) int32."""
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    vals, idxs = jax.lax.top_k(logits, top_cap)  # (b, cap) desc order
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = vals / temp

    # top-k mask within the candidate window
    ranks = jnp.arange(top_cap)[None, :]
    k = jnp.where(top_k[:, None] <= 0, top_cap, top_k[:, None])
    keep_k = ranks < jnp.minimum(k, top_cap)

    # top-p (nucleus) mask: keep the smallest prefix with cumprob >= top_p,
    # i.e. keep entries whose *preceding* cumulative mass is < top_p.
    probs = jax.nn.softmax(jnp.where(keep_k, scaled, -jnp.inf), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]

    keep = keep_k & keep_p
    if min_p is not None:
        # min-p (vLLM min_p role): drop candidates whose post-temperature
        # probability is below min_p * max_prob. Row 0 of the descending
        # top-k IS the max-prob candidate.
        keep = keep & (probs >= min_p[:, None] * probs[:, 0:1])
    keep = keep.at[:, 0].set(True)  # never mask the argmax candidate
    masked = jnp.where(keep, scaled, -jnp.inf)

    def row_gumbel(kd):
        return jax.random.gumbel(
            jax.random.wrap_key_data(kd, impl="threefry2x32"), (top_cap,)
        )

    gumbel = jax.vmap(row_gumbel)(key_data)
    choice = jnp.argmax(masked + gumbel, axis=-1)  # (b,)
    sampled_ids = jnp.take_along_axis(
        idxs, choice[:, None], axis=-1
    ).squeeze(-1).astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids)


def apply_penalties(
    logits: jax.Array,  # (b, vocab) float32
    output_mask: jax.Array,  # (b, vocab) bool: token appeared in output
    output_counts: jax.Array,  # (b, vocab) float32: occurrences in output
    presence: jax.Array,  # (b,)
    frequency: jax.Array,  # (b,)
    repetition: jax.Array,  # (b,)
) -> jax.Array:
    """OpenAI-style presence/frequency + HF-style repetition penalties."""
    logits = logits - presence[:, None] * output_mask
    logits = logits - frequency[:, None] * output_counts
    rep = repetition[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    return jnp.where(output_mask, penalized, logits)


# device-side stop masks (elastic fused decode): the pad token a frozen
# lane's sampled slot is pinned to. 0 is safe — the host consumes only
# the per-lane valid counts, never the pinned slots.
STOP_PAD_TOKEN = 0

# unified ragged dispatch: the sentinel a NON-prefill lane's sampled
# first-token slot is pinned to inside the lane-typed round (negative —
# can never collide with a real token id, unlike STOP_PAD_TOKEN whose
# slots are guarded by valid counts instead). Hosts must only consume
# rows where the value is >= 0, and the engine asserts exactly that.
RAGGED_IDLE_TOKEN = -1


def stop_hit(
    tokens: jax.Array,  # (b,) int32 just-sampled tokens
    eos_ids: jax.Array,  # (b,) int32 per-lane EOS (-1 = ignore_eos/none)
    stop_ids: jax.Array | None,  # (b, cap) int32 padded with -1, or None
) -> jax.Array:
    """Per-lane bool: the sampled token is that lane's EOS or one of
    its stop_token_ids. Shared by the fused decode scan so the device
    check can never drift from one copy of the semantics; the
    min_tokens/max_tokens gates are applied by the caller (they depend
    on the scan's per-lane append counters, not on the token). -1
    sentinels never match (token ids are non-negative)."""
    hit = tokens == eos_ids
    if stop_ids is not None:
        hit = hit | jnp.any(tokens[:, None] == stop_ids, axis=1)
    return hit


LOGPROB_CAP = 20  # static top-N bucket; hosts slice to the requested N


def token_logprobs(
    logits: jax.Array,  # (b, vocab) float32 — post-penalty model logits
    tokens: jax.Array,  # (b,) int32 chosen tokens
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-row chosen-token logprob + top-LOGPROB_CAP alternatives.

    Computed from log_softmax of the raw (pre-temperature) logits — the
    model's distribution, matching vLLM's logprobs semantics. Returns
    (chosen (b,), top_vals (b, CAP), top_ids (b, CAP) int32)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(
        lp, tokens[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    top_vals, top_ids = jax.lax.top_k(lp, LOGPROB_CAP)
    return chosen, top_vals, top_ids.astype(jnp.int32)
