"""Engine configuration."""

from __future__ import annotations

from dataclasses import dataclass, field

from production_stack_tpu.models.config import ModelConfig, get_model_config


@dataclass
class EngineConfig:
    model: str = "pst-tiny-debug"
    tokenizer: str | None = None  # defaults to model path; "byte" for tests
    # optional Jinja chat-template override (string or file path) applied
    # over whatever the tokenizer ships (reference: helm chatTemplate)
    chat_template: str | None = None
    dtype: str = "bfloat16"
    cache_dtype: str = "bfloat16"
    seed: int = 0

    # KV cache sizing: explicit block count, or fraction of HBM after weights
    block_size: int = 32
    num_kv_blocks: int | None = None
    hbm_utilization: float = 0.9

    # scheduling
    # vLLM --scheduling-policy: "fcfs" (arrival order) or "priority"
    # (requests carry an integer `priority`; lower = served first,
    # preemption evicts the LOWEST-priority victim)
    scheduling_policy: str = "fcfs"
    max_model_len: int | None = None  # None -> model's max
    max_num_seqs: int = 8
    max_prefill_chunk: int = 512
    enable_chunked_prefill: bool = True
    # cross-sequence prefill packing: up to this many sequences' prompt
    # chunks run in ONE dispatch (N concurrent arrivals cost ~1 program
    # instead of N — burst TTFT). 1 = round-2 behavior. Group size is
    # bucketed to powers of two, so the jit compile space grows by
    # log2(max_prefill_seqs) variants.
    max_prefill_seqs: int = 8
    enable_prefix_caching: bool = True
    # max consecutive prefill chunks while decodes wait (bounded ITL);
    # 0 = prefill always wins (round-1 behavior)
    decode_interleave: int = 1
    # fused decode iterations per dispatch (vLLM --num-scheduler-steps):
    # sampling (incl. presence/frequency/repetition penalties, whose
    # token counts ride on device through the scan) runs on device and K
    # tokens come back in ONE host fetch, amortising the dispatch/fetch
    # RTT. Must be <= block_size. With adaptive_decode_k this is the
    # CAP: the scheduler sizes each round from pow2 buckets up to it.
    num_scheduler_steps: int = 1
    # elastic fused decode, part 1 — device-side stop masks: EOS, the
    # request's stop_token_ids, and a remaining-max_tokens countdown
    # are evaluated INSIDE the fused K-step scan. A lane that finishes
    # mid-round freezes (sampled slot pinned to the pad token, KV-slot
    # writes redirected to the trash slot, penalty/guided state
    # updates masked) and the dispatch returns per-lane valid counts,
    # so the host applies exactly the generated tokens instead of
    # discarding overshoot after the fetch; a round whose lanes all
    # finish exits early (lax.while_loop). The round-5 chip window
    # measured K=32 wasting 28% of sampled slots on exactly this
    # overshoot. False (--no-device-stop) keeps the fixed-trip scan as
    # the chip-window A/B control. Host-side stop STRINGS still
    # resolve on the host (text matching cannot run on device).
    # Multihost engines ignore this (the broadcast wire ships host
    # token lists, not stop matrices).
    device_stop: bool = True
    # elastic fused decode, part 2 — admission-aware adaptive K: the
    # scheduler picks each round's K from pow2 buckets (precompiled by
    # --precompile-serving) instead of always dispatching the full
    # num_scheduler_steps. A queued/cold prefill clamps K low so a
    # long fused round never starves admission (the K=16 TTFT-blowup
    # failure mode, PERF.md round 5 window 2), and the batch's max
    # remaining-token budget bounds K so the last rounds of short
    # answers stop dispatching full-K programs (the K=32 waste mode).
    # False (--no-adaptive-decode-k) keeps the fixed-K behavior.
    adaptive_decode_k: bool = True
    # double-buffered decode (vLLM --async-scheduling role): dispatch
    # decode round N+1 chained on round N's ON-DEVICE sampled tokens
    # before fetching round N, so the device never idles on the
    # host<->device RTT. Requires num_scheduler_steps > 1; rounds with
    # logit penalties, lane-set changes, or lanes within K tokens of
    # finishing fall back to the synchronous path (outputs stay
    # bit-identical). Ignored under multihost (followers replay host
    # token lists). Default OFF: the round-5 hardware sweep measured
    # sync-packed above async-packed at K=8 (chained rounds delay
    # prefill admission), and async taking precedence would make
    # prefetch_decode below dead code — h2d prefetch gets the overlap
    # benefit at synchronous admission instead.
    async_decode: bool = False
    # speculative h2d prefetch: while a fused decode round executes,
    # upload the NEXT round's packed host inputs (positions/ctx/keys
    # advanced by K on the same lanes) and dispatch it chained on the
    # on-device sampled tokens when the prediction holds. Removes the
    # serial host->device transfer (~116 ms through a tunneled chip)
    # from the steady-state round critical path with fully synchronous
    # admission (unlike async_decode, at most ONE round is in flight).
    # Requires num_scheduler_steps > 1; single-device; off multihost.
    prefetch_decode: bool = True
    # pipelined prefill: (1) every prefill dispatch ships ONE packed i32
    # host->device buffer (tokens/positions/write slots/tables/sampling
    # args fused, mirroring the decode pack) instead of ~8 small
    # transfers that each pay link latency through a tunneled chip;
    # (2) while chunk N computes on device, chunk N+1's buffer is built
    # and uploaded so the h2d overlaps compute; (3) cold multi-chunk
    # prompts chain their chunks back-to-back without a host round-trip
    # in between (only the final chunk's sampled token is fetched), and
    # a staged-and-ready chunk is admitted as zero-cost by the
    # scheduler's decode interleave. Outputs are bit-identical to the
    # serial path (tests/test_prefill_pipeline.py). False = the
    # pre-pipeline per-array upload path (--no-prefill-pipeline, the
    # bench attribution control).
    prefill_pipeline: bool = True
    # unified ragged prefill+decode dispatch (Ragged Paged Attention
    # role, PAPERS.md): when a round has BOTH mid-prefill runners and
    # decode-ready lanes, the scheduler plans ONE lane-typed round
    # (scheduler.plan_ragged_round) and the engine dispatches ONE
    # device program (model_runner.ragged_dispatch) whose packed h2d
    # buffer carries prefill-chunk lanes and fused decode lanes
    # together — the prefill/decode interleave throttle and the
    # admission-K clamp for in-round prefill work dissolve, a waiting
    # prompt's chunk runs in the very next round, and the decode half
    # keeps the device stop masks + staged h2d prefetch. Tokens are
    # bit-identical to the split path (tests/test_ragged_dispatch.py).
    # False (--no-ragged-dispatch) keeps the split alternating rounds
    # as the bench attribution control; multihost engines, async-
    # chained decode, and meshed (tp/pp) engines always split.
    ragged_dispatch: bool = True
    # single-kernel ragged paged attention (the device half of the
    # Ragged Paged Attention design): route every Pallas attention
    # call — decode rounds, packed prefill groups, and the mixed
    # lane-typed rounds above — through ONE batched-grid kernel
    # (ops/pallas_attention.ragged_paged_attention) whose grid
    # iterates a flattened query-row space with per-lane metadata in
    # scalar-prefetch SMEM: decode lanes contribute one row, prefill
    # lanes their chunk's q-tiles, so ANY lane mix is one kernel
    # launch with no cross-lane padding, and the packed-prefill /
    # ragged-round program variants key on padded ROW-count buckets
    # instead of the (group, chunk) lane-mix grid (fewer compiles =
    # smaller cold-start tax). Tokens + logical KV are bit-identical
    # to the composed per-lane kernels (tests/test_pallas_attention
    # .py, tests/test_ragged_dispatch.py). Only effective with
    # attention_impl=pallas; False (--no-ragged-kernel) keeps the
    # composed per-lane kernels as the bench attribution control.
    ragged_kernel: bool = True
    # compile every steady-state serving program shape at startup
    # (full-chunk + resume-tail prefill, packed groups, fused-K decode,
    # per ctx bucket) so no XLA compile lands inside a live request's
    # TTFT/ITL — through a remote/tunneled chip one compile is tens of
    # seconds. Costs minutes of startup the FIRST time; the persistent
    # compile cache (JAX_COMPILATION_CACHE_DIR) makes later restarts
    # cheap. Multihost: broadcast so follower hosts compile ahead too.
    precompile_serving: bool = False
    # speculative decoding (vLLM --speculative-config ngram role):
    # propose up to this many draft tokens by prompt-lookup (the last
    # n-gram's previous continuation in the context) and verify them in
    # ONE prefill-shaped forward — each fully-accepted verify replaces
    # up to K sequential decode dispatches. Greedy-only (temperature 0,
    # no penalties/logprobs) and engages at decode batch 1, where the
    # per-step RTT dominates; everything else falls back to the normal
    # decode path with identical outputs. 0 = off.
    num_speculative_tokens: int = 0
    ngram_prompt_lookup_max: int = 3
    ngram_prompt_lookup_min: int = 1

    # long-context serving (context-parallel ring prefill,
    # engine/long_prefill.py): a prompt whose UNCACHED remainder
    # exceeds this many tokens leaves the chunked-prefill lane and runs
    # as sp-sharded ring chunks on a ("tp", "sp") mesh
    # (parallel/long_context.py), its layer-stacked KV landing in the
    # paged cache through the PR 4 donated-import primitives — decode
    # afterwards is the normal paged path, tokens bit-identical to a
    # chunked-prefill control (tests/test_long_context_serving.py).
    # The long lane never blocks ragged/decode rounds for other users:
    # one enqueue-only chunk dispatch (plus at most one landed block
    # batch) per engine step. None = off. Requires
    # context_parallel_size > 1; single-process engines only (multihost
    # and pipeline-parallel engines always serve chunked).
    long_prefill_threshold: int | None = None
    # ring chunk length in tokens (rounded up to a multiple of the ring
    # size and the KV block size); the padded sequence ladder is
    # chunk x pow2, so program variants stay O(log max_model_len)
    long_prefill_chunk: int = 2048
    # sp mesh axis size for the ring (0/1 = no sp mesh). The ring uses
    # tensor_parallel_size x context_parallel_size devices, preferring
    # devices past the serving one(s) when the host has spares.
    context_parallel_size: int = 0

    # parallelism (tensor-parallel size over the ICI mesh)
    tensor_parallel_size: int = 1
    # pipeline parallelism: layers (and their KV) shard over a pp mesh
    # axis; every engine step is one SPMD program with ppermute stage
    # handoffs (parallel/pp_serving.py; the reference's ray-cluster
    # pipelineParallelSize capability). Composes with tp: pp x tp chips.
    pipeline_parallel_size: int = 1
    # one engine spanning the hosts of a multi-host slice (jax.distributed
    # SPMD; host 0 schedules + serves HTTP, followers replay its steps)
    multihost: bool = False

    # serving
    served_model_name: str | None = None
    enable_lora: bool = False
    max_loras: int = 4
    max_lora_rank: int = 16
    # OpenAI tool calling (engine/tools.py; vLLM flag names, reference
    # tutorial 13): auto tool choice requires the explicit opt-in
    enable_auto_tool_choice: bool = False
    tool_call_parser: str = "hermes"
    # require `Authorization: Bearer <key>` on /v1/* (vLLM --api-key)
    api_key: str | None = None

    # attention implementation: "auto" | "xla" | "pallas"
    attention_impl: str = "auto"

    # disaggregated prefill/decode role: None (undeclared) | "prefill"
    # | "decode" | "both". Prefill/both engines serve KV chains over
    # kv_transfer_config["listen"] (kv/transfer.py); decode/both engines
    # pull through a PeerTier at kv_transfer_config["peer"] (comma list
    # of peer addresses — a prefill engine or a cache server, address-
    # interchangeably). The role is advertised on the /v1/models card so
    # the router's `pd` policy can split the fleet.
    kv_role: str | None = None
    kv_transfer_config: dict = field(default_factory=dict)

    def pd_role(self) -> str | None:
        """Resolved PD role for discovery: the explicit kv_role, else
        inferred from the transfer config ('both' when an engine both
        serves and pulls), else None (not PD-configured)."""
        if self.kv_role in ("prefill", "decode", "both"):
            return self.kv_role
        cfg = self.kv_transfer_config or {}
        listen, peer = cfg.get("listen"), cfg.get("peer")
        if listen and peer:
            return "both"
        if listen:
            return "prefill"
        if peer:
            return "decode"
        return None

    # -- observability ------------------------------------------------
    # per-request lifecycle timeline (tracing/timeline.py): enqueue ->
    # admit -> prefill chunks -> first token -> sampled decode rounds ->
    # preempt/resume -> finish, served by /debug/requests and exported
    # as `engine_request` spans. Recording is append-only host work off
    # the device-dispatch path; False makes every hook a single boolean
    # check (the bench `@trace` A/B measures the difference, PERF.md).
    request_timeline: bool = True
    # finished timelines kept for /debug/requests (bounded ring)
    timeline_ring_size: int = 256
    # engine-side span export: "none" | "log" | "memory" | "otlp"
    # (OTLP/JSON-shaped payloads drained by a watched flush task)
    tracing_exporter: str = "none"

    # KV offload (LMCache-equivalent) tiers
    cpu_offload_bytes: int = 0
    disk_offload_dir: str | None = None
    remote_cache_url: str | None = None
    kv_controller_url: str | None = None
    kv_instance_id: str = "default-instance"
    # zero-stall KV tiering (PR 4): exports are deferred (freed blocks
    # pinned, d2h snapshot enqueued after the step's dispatch, tier IO
    # on the offload worker) and restores are staged (tier fetch + h2d
    # start while the request WAITS; admission lands once the restore
    # does, in-place donated cache update). True restores the pre-PR-4
    # synchronous path — device-sync export inside scheduling, blocking
    # tier reads + whole-cache-copy import on the step loop — as the
    # bench attribution control (--sync-kv-offload / @synckv). Multihost
    # engines always take the synchronous path (the broadcast wire ships
    # host arrays, not device buffers).
    sync_kv_offload: bool = False
    # staged-restore admission budget: how long an admission slot may be
    # held back while the request's tier fetch + h2d staging are in
    # flight, before falling back to recompute-from-scratch. Bounds the
    # damage of a wedged tier (dead remote, slow disk) to one budget per
    # request; the fetch itself typically lands in one tunnel RTT.
    kv_restore_wait_s: float = 2.0

    def __post_init__(self) -> None:
        if self.long_prefill_threshold is not None:
            if self.long_prefill_threshold <= 0:
                raise ValueError(
                    "long_prefill_threshold must be positive (None "
                    "disables the long-prefill lane)"
                )
            if self.context_parallel_size <= 1:
                raise ValueError(
                    "long_prefill_threshold requires "
                    "context_parallel_size > 1 (the ring needs an sp "
                    "mesh axis)"
                )
        if self.scheduling_policy not in ("fcfs", "priority"):
            raise ValueError(
                "scheduling_policy must be 'fcfs' or 'priority'"
            )
        if self.kv_role not in (None, "prefill", "decode", "both"):
            raise ValueError(
                "kv_role must be one of None/'prefill'/'decode'/'both',"
                f" got {self.kv_role!r}"
            )
        # n=0 would make the prompt-lookup window match every position
        # (arr[-0:] is the whole context), degenerating drafts to noise.
        if self.num_speculative_tokens:
            if not (
                1
                <= self.ngram_prompt_lookup_min
                <= self.ngram_prompt_lookup_max
            ):
                raise ValueError(
                    "require 1 <= ngram_prompt_lookup_min <= "
                    f"ngram_prompt_lookup_max, got min="
                    f"{self.ngram_prompt_lookup_min} max="
                    f"{self.ngram_prompt_lookup_max}"
                )

    def model_config(self) -> ModelConfig:
        return get_model_config(self.model)

    def resolved_max_model_len(self) -> int:
        mc = self.model_config()
        if self.max_model_len is None:
            return mc.max_model_len
        return min(self.max_model_len, mc.max_model_len)
