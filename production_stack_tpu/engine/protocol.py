"""OpenAI-compatible request parsing and response building.

Tolerant dict-based parsing (the reference uses extra-field-tolerant pydantic
models, reference: src/vllm_router/protocols.py:11) — unknown fields are
ignored, so clients written for OpenAI/vLLM work unchanged.
"""

from __future__ import annotations

import time
import uuid

from production_stack_tpu.engine.sampling_params import SamplingParams


class ProtocolError(ValueError):
    pass


def make_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def sampling_params_from_request(body: dict) -> SamplingParams:
    try:
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        max_tokens = body.get("max_tokens", body.get("max_completion_tokens"))
        if max_tokens is None:
            max_tokens = 128
        if int(body.get("n", 1)) < 1:
            raise ProtocolError("n must be >= 1")
        logprobs = body.get("logprobs")
        if isinstance(logprobs, bool):
            # chat-style boolean: the chat handler resolves it together
            # with top_logprobs; completions use the integer form
            logprobs = None
        if logprobs is not None:
            logprobs = int(logprobs)
            if not 0 <= logprobs <= 20:
                raise ProtocolError("logprobs must be in [0, 20]")
        prompt_logprobs = body.get("prompt_logprobs")
        if prompt_logprobs is not None:
            prompt_logprobs = int(prompt_logprobs)
            if not 0 <= prompt_logprobs <= 20:
                raise ProtocolError("prompt_logprobs must be in [0, 20]")
        guided_choice = body.get("guided_choice")
        if guided_choice is not None and (
            not isinstance(guided_choice, list)
            or not guided_choice
            or not all(isinstance(c, str) and c for c in guided_choice)
        ):
            raise ProtocolError(
                "guided_choice must be a non-empty list of non-empty "
                "strings"
            )
        guided_json = body.get("guided_json")
        if guided_json is not None and not isinstance(
            guided_json, (dict, str)
        ):
            raise ProtocolError(
                "guided_json must be a JSON schema object or string"
            )
        guided_regex = body.get("guided_regex")
        if guided_regex is not None and not isinstance(guided_regex, str):
            raise ProtocolError("guided_regex must be a string")
        guided_grammar = body.get("guided_grammar")
        if guided_grammar is not None and not isinstance(
            guided_grammar, str
        ):
            raise ProtocolError("guided_grammar must be a string")
        # OpenAI response_format: json_object / json_schema map onto the
        # same constraint machinery (vLLM accepts both spellings)
        rf = body.get("response_format")
        if isinstance(rf, dict) and rf.get("type") in (
            "json_object", "json_schema"
        ):
            if (guided_json is None and guided_choice is None
                    and guided_regex is None and guided_grammar is None):
                if rf["type"] == "json_object":
                    guided_json = {"type": "object"}
                else:
                    try:
                        guided_json = rf["json_schema"]["schema"]
                    except (KeyError, TypeError):
                        raise ProtocolError(
                            "response_format.json_schema.schema required"
                        ) from None
                    if not isinstance(guided_json, (dict, str)):
                        raise ProtocolError(
                            "response_format.json_schema.schema must be "
                            "a JSON schema object"
                        )
        return SamplingParams(
            logprobs=logprobs,
            prompt_logprobs=prompt_logprobs,
            guided_choice=guided_choice,
            guided_json=guided_json,
            guided_regex=guided_regex,
            guided_grammar=guided_grammar,
            max_tokens=int(max_tokens),
            temperature=float(body.get("temperature", 1.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", -1)),
            min_p=float(body.get("min_p", 0.0)),
            logit_bias=body.get("logit_bias"),
            n=int(body.get("n", 1)),
            stop=stop,
            stop_token_ids=list(body.get("stop_token_ids", [])),
            include_stop_str_in_output=bool(
                body.get("include_stop_str_in_output", False)
            ),
            truncate_prompt_tokens=(
                int(body["truncate_prompt_tokens"])
                if body.get("truncate_prompt_tokens") is not None
                else None
            ),
            ignore_eos=bool(body.get("ignore_eos", False)),
            seed=body.get("seed"),
            presence_penalty=float(body.get("presence_penalty", 0.0)),
            frequency_penalty=float(body.get("frequency_penalty", 0.0)),
            repetition_penalty=float(body.get("repetition_penalty", 1.0)),
            min_tokens=int(body.get("min_tokens", 0)),
        )
    except (TypeError, ValueError) as e:
        raise ProtocolError(str(e)) from e


def error_json(message: str, err_type: str = "invalid_request_error",
               code: int = 400) -> dict:
    return {
        "error": {"message": message, "type": err_type, "param": None,
                  "code": code}
    }


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


# -- completions -----------------------------------------------------------
def completion_response(
    request_id: str, model: str, text: str, finish_reason: str | None,
    prompt_tokens: int, completion_tokens: int,
) -> dict:
    return {
        "id": request_id,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "text": text,
                "logprobs": None,
                "finish_reason": finish_reason,
            }
        ],
        "usage": usage_dict(prompt_tokens, completion_tokens),
    }


def completion_chunk(
    request_id: str, model: str, text: str, finish_reason: str | None,
    index: int = 0,
) -> dict:
    return {
        "id": request_id,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": index,
                "text": text,
                "logprobs": None,
                "finish_reason": finish_reason,
            }
        ],
    }


# -- chat completions ------------------------------------------------------
def chat_message_choice(
    index: int, text: str, finish_reason: str | None,
    tool_calls: list[dict] | None = None,
) -> dict:
    """One chat choice dict — the ONE place the tool-call shaping and
    the stop->tool_calls finish-reason flip live (shared by the n=1
    response and the batch/n>1 assembly)."""
    message: dict = {"role": "assistant", "content": text}
    if tool_calls:
        message["tool_calls"] = tool_calls
        message["content"] = text or None
        # OpenAI semantics: a parsed tool call flips "stop" to
        # "tool_calls", but a truncated generation stays "length" so
        # clients can see the call may be incomplete
        if finish_reason == "stop":
            finish_reason = "tool_calls"
    return {
        "index": index,
        "message": message,
        "logprobs": None,
        "finish_reason": finish_reason,
    }


def chat_response(
    request_id: str, model: str, text: str, finish_reason: str | None,
    prompt_tokens: int, completion_tokens: int,
    tool_calls: list[dict] | None = None,
) -> dict:
    return {
        "id": request_id,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            chat_message_choice(0, text, finish_reason, tool_calls)
        ],
        "usage": usage_dict(prompt_tokens, completion_tokens),
    }


def usage_tail_chunk(
    request_id: str, model: str, chat: bool,
    prompt_tokens: int, completion_tokens: int,
) -> dict:
    """stream_options.include_usage: the final empty-choices chunk."""
    tail = (
        chat_chunk(request_id, model, {}, None)
        if chat
        else completion_chunk(request_id, model, "", None)
    )
    tail["choices"] = []
    tail["usage"] = usage_dict(prompt_tokens, completion_tokens)
    return tail


def chat_chunk(
    request_id: str, model: str, delta: dict, finish_reason: str | None,
    index: int = 0,
) -> dict:
    return {
        "id": request_id,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {"index": index, "delta": delta,
             "finish_reason": finish_reason}
        ],
    }


def multi_choice_response(
    request_id: str, model: str, chat: bool,
    choices: list[dict], prompt_tokens: int, completion_tokens: int,
) -> dict:
    """Batch/n>1 response envelope; `choices` are pre-shaped dicts."""
    return {
        "id": request_id,
        "object": "chat.completion" if chat else "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": usage_dict(prompt_tokens, completion_tokens),
    }


def model_card(
    name: str, root: str | None = None,
    kv_instance_id: str | None = None,
    kv_role: str | None = None,
    max_model_len: int | None = None,
    sp_size: int | None = None,
) -> dict:
    card = {
        "id": name,
        "object": "model",
        "created": int(time.time()),
        "owned_by": "production-stack-tpu",
        "root": root or name,
        "parent": None,
        # the engine's admitted context window: the router's
        # context-window filter skips backends whose window is smaller
        # than the prompt and 413s when no backend qualifies
        "max_model_len": max_model_len,
        "permission": [],
    }
    if sp_size:
        # long-prefill capability: the ring's sp mesh axis size (the
        # engine serves 64k-128k prompts as context-parallel ring
        # prefill rather than one-chip chunked prefill)
        card["sp_size"] = sp_size
    if kv_instance_id is not None:
        # advertised so the router's kvaware/ttft logic can map KV
        # controller matches to this endpoint without relying on the
        # id == host:port convention (reference role:
        # src/gateway_inference_extension/kv_aware_picker.go:90-131)
        card["kv_instance_id"] = kv_instance_id
    if kv_role is not None:
        # PD role (prefill/decode/both) for the router's `pd` policy —
        # discovery labels this endpoint without k8s label plumbing
        card["kv_role"] = kv_role
    return card
