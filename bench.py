"""Benchmark: serving throughput + TTFT on the real TPU chip.

Workload shape follows the reference's multi-round-qa definition scaled to
one chip (reference: benchmarks/multi-round-qa/run.sh — shared system
prompt + long per-user history + ~100-token answers): concurrent sessions
with a shared prefix exercise chunked prefill, prefix caching, continuous
batching, and paged decode together.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is the fraction of the HBM-bandwidth decode roofline achieved
(roofline tok/s = batch * HBM_BW / model_bytes — every decode step must
stream the weights once; the reference repo commits no absolute numbers to
compare against, see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("PST_LOG_LEVEL", "WARNING")  # keep stdout JSON-only

# Persistent XLA compilation cache: chip windows through the tunnel can be
# as short as ~20 min (TPU_ATTEMPTS.log 2026-07-31: up 01:01, dead before
# the ~13 min of per-config compiles finished), so a retried session must
# not re-pay them. With the cache, warmup/precompile of an already-seen
# config is a disk read instead of a tunnel compile. Harmless if the PJRT
# plugin can't serialize executables — jax logs a warning and recompiles.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import numpy as np  # noqa: E402

MODEL = os.environ.get("PST_BENCH_MODEL", "llama-3.2-3b")
# north-star config is Llama-3-8B tp=8 on a v5e-8; the driver exposes one
# chip, so the default serves the largest family member that fits it with
# the Pallas kernels engaged (3B, head_dim 128 — the 1B's head_dim 64
# falls back to the XLA path, see engine/model_runner.py).
# On a full slice: PST_BENCH_MODEL=llama-3-8b PST_BENCH_TP=8 python bench.py
TP = int(os.environ.get("PST_BENCH_TP", "1"))
NUM_USERS = int(os.environ.get("PST_BENCH_USERS", "16"))
SYSTEM_PROMPT_TOK = int(os.environ.get("PST_BENCH_SYS_TOK", "512"))
HISTORY_TOK = int(os.environ.get("PST_BENCH_HISTORY_TOK", "1024"))
ANSWER_TOK = int(os.environ.get("PST_BENCH_ANSWER_TOK", "100"))
# chat rounds per user (reference: multi-round-qa/run.sh drives 10 rounds
# per session). Rounds 2+ resume from the prefix cache — only the tail
# past the last cached whole block re-prefills — so multi-round is both
# the faithful workload shape AND the one the paged prefix cache exists
# for. All lengths are deterministic (greedy + ignore_eos), so every
# resume-tail bucket is precompiled analytically below.
ROUNDS = int(os.environ.get("PST_BENCH_ROUNDS", "10"))
# tokens appended as the user's next question between rounds
QUESTION_TOK = int(os.environ.get("PST_BENCH_QUESTION_TOK", "64"))
# fused decode iterations per dispatch (amortises the host<->device RTT,
# which dominates through the tunneled chip; see engine/model_runner.py)
SCHED_STEPS = int(os.environ.get("PST_BENCH_SCHED_STEPS", "8"))
# cross-sequence prefill packing group cap (1 = round-2 behavior)
PREFILL_SEQS = int(os.environ.get("PST_BENCH_PREFILL_SEQS", "8"))
# prefill chunk size: bigger chunks = fewer RTT-dominated dispatches per
# cold prompt (the 48-user window-2 run was prefill-bound), at the cost
# of larger programs and coarser decode interleaving
PREFILL_CHUNK = int(os.environ.get("PST_BENCH_PREFILL_CHUNK", "512"))
# double-buffered decode dispatch (0 = synchronous fetch per round).
# Default OFF: the round-5 hardware sweep measured sync-packed at 141.8
# tok/s/chip vs async-packed 117.6 — chained decode keeps the device
# busy and delays prefill admission (p50 TTFT 0.78s -> 2.94s), costing
# more than the fetch overlap buys at K=8
ASYNC_DECODE = os.environ.get("PST_BENCH_ASYNC", "0") == "1"
# speculative h2d prefetch (engine prefetch_decode): stage the next
# fused round's packed inputs during the current round's fetch
PREFETCH = os.environ.get("PST_BENCH_PREFETCH", "1") == "1"
# pipelined prefill (engine prefill_pipeline): fused h2d buffer per
# prefill dispatch + staged chunk uploads + cold-prompt chunk chaining.
# Attribution slots: BENCH_SWEEP_pfpipe.json (on, default) vs
# BENCH_SWEEP_nopfpipe.json (@nopfpipe label modifier)
PREFILL_PIPELINE = os.environ.get("PST_BENCH_PREFILL_PIPELINE", "1") == "1"
# request tracing (engine request_timeline + memory span exporter): the
# overhead A/B pinning the zero-cost-when-disabled claim. Default OFF so
# every existing sweep stays a tracing-free control; @trace enables.
# Slots: BENCH_SWEEP_trace.json (on) vs the matching untraced config
TRACE = os.environ.get("PST_BENCH_TRACE", "0") == "1"
# elastic fused decode (engine device_stop + adaptive_decode_k): stop
# conditions evaluated INSIDE the fused scan (finished lanes freeze,
# per-lane valid counts, whole-round early exit) and per-round K sized
# from pow2 buckets under admission pressure / remaining budget.
# Default ON (the engine default); @noelastic pins the fixed-trip
# fixed-K control for the chip-window A/B. Slots:
# BENCH_SWEEP_elastic.json (on) vs the matching @noelastic control
ELASTIC = os.environ.get("PST_BENCH_ELASTIC", "1") == "1"
# unified ragged prefill+decode dispatch (engine ragged_dispatch):
# mixed rounds run prefill-chunk lanes and fused decode lanes in ONE
# lane-typed device program — the interleave throttle and the
# admission-K clamp for in-round prefill work dissolve. Default ON
# (the engine default); @noragged pins the split alternating rounds
# as the attribution control. Slots: BENCH_SWEEP_ragged.json (on) vs
# the matching @noragged control
RAGGED = os.environ.get("PST_BENCH_RAGGED", "1") == "1"
# single-kernel ragged paged attention (engine ragged_kernel): ONE
# batched-grid Pallas kernel serves any lane mix (decode rows +
# prefill q-tiles share the grid), and program variants key on padded
# row-count buckets instead of the (group, chunk) lane-mix grid.
# Default ON (the engine default, effective only under
# attention_impl=pallas i.e. on a real chip); @norpakernel pins the
# composed per-lane kernels as the attribution control. Slots:
# BENCH_SWEEP_rpa.json (on) vs the matching @norpakernel control
RAGGED_KERNEL = os.environ.get("PST_BENCH_RAGGED_KERNEL", "1") == "1"
# KV tiering workload (@kvoff): cap the HBM pool so the multi-round
# working set churns through the cpu/disk offload tiers — the zero-stall
# async export/staged-restore measurement. PST_BENCH_KV_BLOCKS overrides
# the cap (default: ~1.15x the peak ACTIVE working set, so finished
# sessions' prefixes spill between rounds while running lanes always
# fit). Slots: BENCH_SWEEP_kvoff.json (async tiering, default) vs
# BENCH_SWEEP_kvoff_sync.json (@synckv -> --sync-kv-offload control)
KV_OFFLOAD = os.environ.get("PST_BENCH_KV_OFFLOAD", "0") == "1"
KV_BLOCKS = int(os.environ.get("PST_BENCH_KV_BLOCKS", "0"))
# disaggregated prefill/decode (@pd): round-1 prompts prefill on a
# SEPARATE prefill-role engine (own step thread, in-process
# KVTransferServer) and the measured decode engine pulls the chain
# through its PeerTier staged restore before decoding — the PD data
# plane end to end, colocated on ONE chip (both engines share the
# device, so weights sit in HBM twice and device work serializes;
# this measures the transfer machinery's cost/win shape, it
# UNDERSTATES the multi-chip win where prefill compute is genuinely
# offloaded — run it with the small-model configs). Rounds 2+ resume
# directly on the decode engine (prefix-affine, the router pd
# policy's PPD behavior). @nopd pins the single-engine control.
# Slots: BENCH_SWEEP_pd.json vs the matching @nopd control (PERF.md)
PD = os.environ.get("PST_BENCH_PD", "0") == "1"
SYNC_KV = os.environ.get("PST_BENCH_SYNC_KV", "0") == "1"
# shared KV cache server (@remotekv, requires @kvoff): run an
# in-process kv.cache_server and wire the engine's RemoteTier at it —
# the LMCache-like topology (small host RAM buffer + cluster cache, NO
# local disk tier): exports write through as write-behind batched PUT
# frames, and resumes whose prefix aged out of the cpu buffer restore
# over the wire as ONE get_chain pull instead of recomputing.
# @noremotekv pins the local-tiers-only control (the @kvoff default).
# Slots: BENCH_SWEEP_kvremote.json vs the matching @noremotekv control
KV_REMOTE = os.environ.get("PST_BENCH_KV_REMOTE", "0") == "1"
# long-context scenario (@longctx): instead of the multi-round QA
# workload, sweep ONE prompt per length over 8k -> 128k tokens and
# record TTFT vs length + per-phase attribution (ring / d2h / land /
# overflow) + the HBM high-water mark. @nolongctx runs the same sweep
# with the ring lane OFF (chunked-prefill control — the A/B the staged
# BENCH_SWEEP_longctx.json entry in PERF.md measures).
LONGCTX = os.environ.get("PST_BENCH_LONGCTX", "0") == "1"
LONGCTX_RING = os.environ.get("PST_BENCH_LONGCTX_RING", "1") == "1"
LONGCTX_SP = int(os.environ.get("PST_BENCH_LONGCTX_SP", "4"))
LONGCTX_THRESHOLD = int(
    os.environ.get("PST_BENCH_LONGCTX_THRESHOLD", "4096")
)
LONGCTX_CHUNK = int(os.environ.get("PST_BENCH_LONGCTX_CHUNK", "2048"))
LONGCTX_LENS = [
    int(x)
    for x in os.environ.get(
        "PST_BENCH_LONGCTX_LENS", "8192,16384,32768,65536,131072"
    ).split(",")
    if x.strip()
]
LONGCTX_ANSWER_TOK = int(
    os.environ.get("PST_BENCH_LONGCTX_ANSWER_TOK", "16")
)
CPU_OFFLOAD_MB = int(os.environ.get("PST_BENCH_CPU_OFFLOAD_MB", "2048"))
DISK_OFFLOAD_DIR = os.environ.get(
    "PST_BENCH_DISK_DIR", "/tmp/pst-bench-kv"
)
# pre-compile the packed-prefill buckets the timed run will hit so no
# XLA compile lands inside a TTFT measurement (each tunnel compile is
# tens of seconds)
PRECOMPILE = os.environ.get("PST_BENCH_PRECOMPILE", "1") == "1"
HBM_BW_GBPS = float(os.environ.get("PST_BENCH_HBM_BW", "819"))  # v5e
QPS = float(os.environ.get("PST_BENCH_QPS", "2.0"))  # arrival pacing


def _init_backend_or_die(timeout_s: float = 60.0, retries: int = 1):
    """Initialize the jax backend with a hard deadline.

    Round-1 lesson: `jax.devices()` can hang indefinitely when the TPU
    backend is unreachable, leaving the driver to kill the process with no
    diagnostic. Probe backend init in a daemon thread with a bounded wait;
    on failure emit the ONE JSON line the driver records (with an `error`
    field) and exit non-zero fast.
    """
    import threading

    err = "unknown"
    for attempt in range(retries + 1):
        box: dict = {}

        def probe() -> None:
            try:
                import jax

                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 - report any init failure
                box["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            # a hung probe still holds the import/backend-init lock, so a
            # retry would block on the same state — abort immediately
            err = f"jax backend init timed out after {timeout_s:.0f}s"
            print(f"# backend init: {err}", file=sys.stderr)
            break
        if "error" in box:
            err = box["error"]
        else:
            return box["devices"]
        print(f"# backend init attempt {attempt + 1} failed: {err}",
              file=sys.stderr)
    print(json.dumps({
        "metric": "bench-aborted: jax backend unavailable",
        "value": 0.0,
        "unit": "gen_tokens/s/chip",
        "vs_baseline": 0.0,
        "error": err,
    }))
    sys.exit(1)


def main() -> None:
    if os.environ.get("PST_BENCH_SWEEP", "0") == "1":
        # the sweep parent never dials the chip: each config runs in its
        # own subprocess (below), so it must not hold the chip lock
        _run_sweep()
        return

    # chip-session hygiene: one TPU process at a time, SIGTERM-only stop
    from production_stack_tpu.utils import chip_guard
    from production_stack_tpu.utils.chip_guard import ChipBusyError

    try:
        _chip_lock = chip_guard.engage()  # noqa: F841 — held for run life
    except ChipBusyError as e:
        print(f"# {e}", file=sys.stderr)
        print(json.dumps({
            "metric": "bench-aborted: chip lock held by another process",
            "value": 0.0,
            "unit": "gen_tokens/s/chip",
            "vs_baseline": 0.0,
            "error": str(e)[:200],
        }))
        sys.exit(1)
    devices = _init_backend_or_die()
    import jax

    print(f"# backend: {devices[0].platform} x{len(devices)}",
          file=sys.stderr)

    print(json.dumps(run_config(
        SCHED_STEPS, PREFILL_SEQS, ASYNC_DECODE,
        os.environ.get("PST_BENCH_LABEL", "default"),
    )))


def _parse_sweep_labels(spec: str) -> list[tuple]:
    """Parse the sweep config list. Base labels are
    k<N>-{sync|async}-{packed|nopack}; optional @-suffixes override the
    per-config workload env (the reference's run.sh sweeps QPS across
    one deployment — this lets one chip session walk the serving
    curve): k8-sync-packed@qps4@u32@r1 -> QPS=4, USERS=32, ROUNDS=1;
    @chunk<N> sets the prefill chunk; @nopfx disables h2d prefetch.
    Returns (label, k, prefill_seqs, async, env_overrides) tuples."""
    configs: list[tuple] = []
    for label in [x.strip() for x in spec.split(",") if x.strip()]:
        base, *mods = label.split("@")
        overrides: dict[str, str] = {}
        for m in mods:
            # exact-keyword modifiers FIRST: @ragged would otherwise
            # match the r<N> rounds prefix rule below
            if m == "ragged":
                overrides["PST_BENCH_RAGGED"] = "1"
            elif m == "noragged":
                overrides["PST_BENCH_RAGGED"] = "0"
            elif m == "rpa":
                overrides["PST_BENCH_RAGGED_KERNEL"] = "1"
            elif m == "norpakernel":
                overrides["PST_BENCH_RAGGED_KERNEL"] = "0"
            elif m == "remotekv":  # before the r<N> rounds prefix rule
                overrides["PST_BENCH_KV_REMOTE"] = "1"
            elif m == "noremotekv":
                overrides["PST_BENCH_KV_REMOTE"] = "0"
            elif m.startswith("qps"):
                overrides["PST_BENCH_QPS"] = str(float(m[3:]))
            elif m.startswith("chunk"):
                overrides["PST_BENCH_PREFILL_CHUNK"] = str(int(m[5:]))
            elif m.startswith("u"):
                overrides["PST_BENCH_USERS"] = str(int(m[1:]))
            elif m.startswith("r"):
                overrides["PST_BENCH_ROUNDS"] = str(int(m[1:]))
            elif m == "nopfx":
                overrides["PST_BENCH_PREFETCH"] = "0"
            elif m == "nopfpipe":
                overrides["PST_BENCH_PREFILL_PIPELINE"] = "0"
            elif m == "trace":
                overrides["PST_BENCH_TRACE"] = "1"
            elif m == "elastic":
                overrides["PST_BENCH_ELASTIC"] = "1"
            elif m == "noelastic":
                overrides["PST_BENCH_ELASTIC"] = "0"
            elif m == "kvoff":
                overrides["PST_BENCH_KV_OFFLOAD"] = "1"
            elif m == "synckv":
                overrides["PST_BENCH_SYNC_KV"] = "1"
            elif m == "pd":
                overrides["PST_BENCH_PD"] = "1"
            elif m == "nopd":
                overrides["PST_BENCH_PD"] = "0"
            elif m == "longctx":
                # long-context scenario: 8k -> 128k prompt-length sweep
                # served by the context-parallel ring lane
                overrides["PST_BENCH_LONGCTX"] = "1"
            elif m == "nolongctx":
                # same sweep on the chunked-prefill control (the A/B)
                overrides["PST_BENCH_LONGCTX"] = "1"
                overrides["PST_BENCH_LONGCTX_RING"] = "0"
            else:
                raise ValueError(
                    f"bad sweep label modifier {m!r} in {label!r}: want "
                    "qps<F> | u<N> | r<N> | chunk<N> | nopfx | nopfpipe "
                    "| trace | elastic | noelastic | ragged | noragged "
                    "| rpa | norpakernel | kvoff | synckv | remotekv "
                    "| noremotekv | pd | nopd | longctx | nolongctx"
                )
        if ("PST_BENCH_SYNC_KV" in overrides
                and "PST_BENCH_KV_OFFLOAD" not in overrides):
            # fail fast: @synckv without @kvoff would silently measure a
            # NO-tiering config as the "sync control" — a scarce chip
            # window must not burn on a corrupted A/B
            raise ValueError(
                f"{label!r}: @synckv requires @kvoff (the sync path "
                "only differs once the KV tiers are enabled)"
            )
        if (overrides.get("PST_BENCH_KV_REMOTE") == "1"
                and "PST_BENCH_KV_OFFLOAD" not in overrides):
            # same honesty gate: the remote tier only sees traffic once
            # the capped-HBM eviction workload is on
            raise ValueError(
                f"{label!r}: @remotekv requires @kvoff (shared-cache "
                "traffic only exists under the capped-HBM workload)"
            )
        kpart, mode, pack = base.split("-")
        # fail fast on typos: a scarce chip window must not silently run
        # the sync path under an "asynch" label
        if (not kpart.startswith("k") or mode not in ("sync", "async")
                or pack not in ("packed", "nopack")):
            raise ValueError(
                f"bad sweep config label {label!r}: want "
                "k<N>-{sync|async}-{packed|nopack}[@qps<F>|@u<N>|@r<N>"
                "|@chunk<N>|@nopfx|@nopfpipe|@trace|@elastic"
                "|@noelastic|@ragged|@noragged|@rpa|@norpakernel"
                "|@kvoff|@synckv|@remotekv|@noremotekv|@pd|@nopd"
                "|@longctx|@nolongctx]"
            )
        configs.append((
            label,
            int(kpart[1:]),
            PREFILL_SEQS if pack == "packed" else 1,
            mode == "async",
            overrides,
        ))
    return configs


def _run_sweep() -> None:
    """The full measurement matrix: K=1 control, K=8, packing on/off,
    async on/off — ONE SUBPROCESS PER CONFIG. Process exit is the only
    HBM-release primitive that works reliably through the tunnel: the
    round-5 sweep showed an in-process engine.shutdown() leaves the old
    engine's params+KV live long enough that the next config's
    allocations RESOURCE_EXHAUST the chip. Results stream into
    BENCH_SWEEP.json after EVERY config so a mid-sweep wedge still
    leaves evidence; the best row is the driver-contract stdout line."""
    import subprocess

    # config labels are self-describing ("k{K}-{sync|async}-{packed|nopack}")
    # and the list is env-overridable so a short chip window can run the
    # highest-value measurements first:
    #   PST_BENCH_SWEEP_CONFIGS=k8-sync-packed,k16-sync-packed,... bench.py
    spec = os.environ.get(
        "PST_BENCH_SWEEP_CONFIGS",
        "k1-sync-nopack,k{K}-sync-nopack,k{K}-sync-packed,k{K}-async-packed"
    ).replace("{K}", str(SCHED_STEPS))
    configs = _parse_sweep_labels(spec)
    out_path = os.environ.get("PST_BENCH_SWEEP_OUT", "BENCH_SWEEP.json")
    per_config_timeout = float(
        os.environ.get("PST_BENCH_CONFIG_TIMEOUT", "1500")
    )
    results: list[dict] = []
    for label, k, ps, ad, overrides in configs:
        env = dict(os.environ)
        env.pop("PST_BENCH_SWEEP", None)
        env.update(overrides)
        env.update({
            "PST_BENCH_SCHED_STEPS": str(k),
            "PST_BENCH_PREFILL_SEQS": str(ps),
            "PST_BENCH_ASYNC": "1" if ad else "0",
            "PST_BENCH_LABEL": label,
        })
        r, wedged = _run_one_config(label, env, per_config_timeout)
        # every row records whether the config actually measured;
        # watchdog rows carry the explicit marker the K=16 wedge
        # (round 5 window 2) taught us to expect
        r["ok"] = (not r.get("watchdog")
                   and r.get("value", 0.0) > 0.0)
        print(f"# sweep {label}: {json.dumps(r)}", file=sys.stderr)
        results.append(r)
        with open(out_path, "w") as f:
            json.dump({"ts": time.strftime("%FT%TZ", time.gmtime()),
                       "model": MODEL, "results": results}, f, indent=1)
        if wedged:
            break
        if r.get("value", 0.0) == 0.0:
            # config produced no measurement — a config-specific wedge
            # (the K=16 wedge that aborted the whole round-5 matrix) or
            # a dead chip. The child's in-process watchdog fires on
            # HOST time, so its row cannot distinguish the two: probe
            # once (~120 s) and CONTINUE to the remaining configs when
            # the chip answers ({"ok": false, "watchdog": true} stays
            # in the JSON), stop the sweep when it doesn't — otherwise
            # a tunnel drop mid-window (the 01:01 UTC failure mode)
            # burns every remaining config's full timeout
            probe = os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "scripts", "tpu_probe.py")
            pp = subprocess.Popen(
                [sys.executable, probe],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            try:
                rc = pp.wait(timeout=120)
                # rc 2 = chip lock held by another process (e.g. the
                # probe loop's own cycle): the chip is owned, not dead —
                # a config-specific failure must not abandon a live
                # window just because the flock collided
                alive = rc in (0, 2)
            except subprocess.TimeoutExpired:
                # SIGTERM, never SIGKILL — a killed client wedges the
                # chip tunnel (same invariant as the sweep child above)
                pp.terminate()
                try:
                    pp.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
                alive = False
            if not alive:
                print("# sweep: chip no longer answers — stopping",
                      file=sys.stderr)
                break
    best = max(results, key=lambda r: r.get("value", 0.0))
    print(json.dumps(best))


def _run_one_config(
    label: str, env: dict, timeout: float
) -> tuple[dict, bool]:
    """Run ONE sweep config in its own subprocess (chip-session
    hygiene: process exit is the only reliable HBM-release primitive
    through the tunnel). Returns (driver-contract row, child_wedged);
    `child_wedged` means the child ignored SIGTERM and still holds the
    chip flock, so the caller must abort the sweep. Rows from a fired
    watchdog (the child's 1200 s run deadline, or the parent timeout
    here) carry `watchdog: true`; the parent-timeout row additionally
    carries `parent_timeout: true` (child emitted nothing at all).
    Either way the sweep probes chip health before continuing — the
    child watchdog fires on host time, so its row cannot prove the
    chip is alive. Factored out of _run_sweep so the
    watchdog-continue contract is testable without a chip."""
    import subprocess

    timed_out = False
    wedged = False
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env, stdout=subprocess.PIPE, text=True,
    )
    try:
        stdout, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        # SIGTERM, never SIGKILL: the child owns the chip session and
        # must release it via its handler (see utils/chip_guard.py)
        proc.terminate()
        try:
            stdout, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            # the child ignored SIGTERM: it still holds the chip
            # flock, so any further config would fail instantly with
            # ChipBusyError — abort the sweep instead of recording
            # lock errors as measurements (and leaving a zombie)
            stdout = ""
            wedged = True
    # even on timeout, a graceful SIGTERM shutdown (or the child's
    # teardown guard) may have emitted a COMPLETED measurement —
    # prefer it over a synthetic failure row
    r = _last_json(stdout)
    if r is None and timed_out:
        r = {"metric": f"sweep-config-timeout: {label}",
             "value": 0.0, "unit": "gen_tokens/s/chip",
             "vs_baseline": 0.0, "watchdog": True,
             # parent_timeout: the CHILD emitted nothing at all (its
             # own watchdog never even fired) — kept as a distinct
             # marker for sweep-JSON forensics
             "parent_timeout": True,
             "error": f"no result after {timeout:.0f}s"
                      + ("; child unresponsive to SIGTERM, sweep "
                         "aborted" if wedged else "")}
    elif r is None:
        r = {"metric": f"sweep-config-failed: {label}",
             "value": 0.0, "unit": "gen_tokens/s/chip",
             "vs_baseline": 0.0,
             "error": f"exit={proc.returncode}, no JSON line"}
    return r, wedged


def _last_json(stdout: str | None) -> dict | None:
    """Parse the last driver-contract JSON line from a child's stdout."""
    lines = [ln for ln in (stdout or "").splitlines()
             if ln.startswith("{")]
    try:
        return json.loads(lines[-1])
    except (IndexError, ValueError):
        return None


def _arm_watchdog(seconds: float, label: str):
    """Abort (with the driver-contract JSON line) if the run wedges.

    `_init_backend_or_die` bounds backend INIT, but a chip that dies
    MID-run leaves the main thread blocked inside a C call the
    SIGTERM->SystemExit handler cannot interrupt (observed round 5: KV
    alloc sleep-polling a dropped tunnel for 10+ min). A daemon timer
    prints the abort row and hard-exits; os._exit is acceptable here
    because the tunnel session is already dead."""
    import threading

    def fire() -> None:
        print(json.dumps({
            "metric": f"bench-aborted: watchdog ({label})",
            "value": 0.0,
            "unit": "gen_tokens/s/chip",
            "vs_baseline": 0.0,
            # explicit marker: the sweep parent records this row as
            # {"ok": false, "watchdog": true} and CONTINUES with the
            # remaining configs (the K=16 wedge must not abort a
            # scarce chip window's whole matrix)
            "watchdog": True,
            "error": f"{label} exceeded {seconds:.0f}s — chip wedged?",
        }), flush=True)
        os._exit(2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _cache_server_box():
    """@remotekv bench mode: an in-process `kv.cache_server` standing
    in for the cluster's shared cache pod (colocated on this host — the
    wire cost is loopback, so the A/B measures the
    framing/serialization machinery, understating a real network's
    latency but not its protocol overhead)."""
    from production_stack_tpu.kv.cache_server import InProcessCacheServer

    return InProcessCacheServer(capacity_bytes=8 * 2**30)


class _PDPrefiller:
    """@pd bench mode: a colocated prefill-role engine with its own
    step thread and an in-process KVTransferServer, so the measured
    decode engine exercises the REAL PD data plane (phase-1 prefill
    here, chain pull through the decode engine's PeerTier staged
    restore). Both engines share the one chip — device work serializes
    and weights sit in HBM twice, which understates the multi-chip win
    but measures the transfer machinery honestly."""

    def __init__(self, config):
        import queue as _queue

        from production_stack_tpu.engine.llm_engine import LLMEngine
        from production_stack_tpu.engine.sampling_params import (
            SamplingParams,
        )
        from production_stack_tpu.kv.transfer import KVTransferServer

        self.engine = LLMEngine(config)
        self._lock = threading.Lock()
        self._sp1 = SamplingParams(
            max_tokens=1, temperature=0.0, ignore_eos=True
        )
        self._stop = threading.Event()
        self._finished: _queue.Queue = _queue.Queue()
        self._prompts: dict[str, list[int]] = {}
        self._inflight = 0  # guarded by: self._lock
        self.submitted = 0

        # the transfer server wants an AsyncLLMEngine-alike: .engine +
        # ._lock (the lock our step thread holds per step)
        holder: dict = {"ready": threading.Event()}
        outer = self

        class _FakeAsync:
            engine = self.engine
            _lock = outer._lock

        def serve():
            async_mod = __import__("asyncio")

            async def run():
                srv = KVTransferServer(_FakeAsync())
                await srv.start("127.0.0.1", 0)
                holder["srv"] = srv
                holder["port"] = srv.port
                holder["loop"] = async_mod.get_running_loop()
                holder["stop"] = async_mod.Event()
                holder["ready"].set()
                await holder["stop"].wait()
                await srv.stop()

            async_mod.run(run())

        self._srv_thread = threading.Thread(target=serve, daemon=True)
        self._srv_thread.start()
        assert holder["ready"].wait(10), "kv transfer server stalled"
        self._holder = holder
        self.port = holder["port"]
        self.server = holder["srv"]
        self._step_thread = threading.Thread(
            target=self._run, name="pd-prefill-step", daemon=True
        )
        self._step_thread.start()

    def warmup(self, prompts) -> None:
        from production_stack_tpu.engine.sampling_params import (
            SamplingParams,
        )

        with self._lock:
            self.engine.generate(
                prompts,
                SamplingParams(
                    max_tokens=1, temperature=0.0, ignore_eos=True
                ),
            )

    def submit(self, rid: str, tokens: list[int]) -> None:
        with self._lock:
            self._prompts[rid] = tokens
            self.engine.add_request(
                rid, prompt_token_ids=tokens, sampling_params=self._sp1
            )
            self._inflight += 1
            self.submitted += 1

    def drain(self) -> list[tuple[str, list[int]]]:
        """Finished phase-1 requests, ready for the decode engine."""
        import queue as _queue

        out = []
        while True:
            try:
                out.append(self._finished.get_nowait())
            except _queue.Empty:
                return out

    def busy(self) -> bool:
        """True while phase-1 work is in flight OR finished results
        await drain — _inflight decrements at the same moment the
        result is enqueued, so checking it alone would let the bench
        loop exit with undrained requests (dropping them, and every
        later round of their sessions, from the measurement)."""
        with self._lock:
            if self._inflight > 0:
                return True
        return not self._finished.empty()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                busy = self.engine.has_unfinished()
                outs = self.engine.step() if busy else []
                for o in outs:
                    if o.finished:
                        self._inflight -= 1
                        self._finished.put(
                            (o.request_id,
                             self._prompts.pop(o.request_id))
                        )
            if not busy:
                self._stop.wait(0.002)

    def close(self) -> None:
        self._stop.set()
        self._step_thread.join(timeout=5)
        self._holder["loop"].call_soon_threadsafe(
            self._holder["stop"].set
        )
        self._srv_thread.join(timeout=5)
        self.engine.shutdown()


def _run_longctx(label: str) -> dict:
    """@longctx scenario: serve ONE prompt per length over the 8k ->
    128k sweep, recording TTFT vs prompt length, the long-prefill
    per-phase attribution, and the HBM high-water mark. The ring lane
    is on by default (@longctx); @nolongctx pins the chunked-prefill
    control for the A/B."""
    import gc

    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.llm_engine import LLMEngine
    from production_stack_tpu.engine.sampling_params import SamplingParams
    from production_stack_tpu.models.config import get_model_config

    watchdog = _arm_watchdog(
        float(os.environ.get("PST_BENCH_RUN_DEADLINE", "1200")),
        f"longctx[{label}]",
    )
    mc = get_model_config(MODEL)
    lens = [x for x in LONGCTX_LENS
            if x + LONGCTX_ANSWER_TOK <= mc.max_model_len]
    if not lens:
        raise SystemExit(
            f"model {MODEL} (max_model_len={mc.max_model_len}) admits "
            f"none of the sweep lengths {LONGCTX_LENS}"
        )
    ring = LONGCTX_RING
    config = EngineConfig(
        model=MODEL,
        tokenizer="byte",
        dtype="bfloat16",
        cache_dtype="bfloat16",
        block_size=32,
        hbm_utilization=0.85,
        max_model_len=max(lens) + LONGCTX_ANSWER_TOK,
        max_num_seqs=4,
        max_prefill_chunk=PREFILL_CHUNK,
        tensor_parallel_size=TP,
        num_scheduler_steps=SCHED_STEPS,
        device_stop=ELASTIC,
        adaptive_decode_k=ELASTIC,
        long_prefill_threshold=LONGCTX_THRESHOLD if ring else None,
        context_parallel_size=LONGCTX_SP if ring else 0,
        long_prefill_chunk=LONGCTX_CHUNK,
        seed=0,
    )
    t_setup = time.time()
    engine = LLMEngine(config)
    ring_live = engine.long_prefill is not None
    print(
        f"# longctx engine up in {time.time() - t_setup:.1f}s, ring "
        f"{'LIVE' if ring_live else 'OFF'}, "
        f"{engine.runner.num_blocks} KV blocks",
        file=sys.stderr,
    )
    rng = np.random.RandomState(0)
    vocab = engine.runner.model_config.vocab_size
    sp = SamplingParams(
        max_tokens=LONGCTX_ANSWER_TOK, temperature=0.0, ignore_eos=True
    )
    # warm the small buckets so the first sweep point is not all compile
    engine.generate(
        [rng.randint(0, vocab, 256).tolist()],
        SamplingParams(max_tokens=2, temperature=0.0, ignore_eos=True),
    )

    def _peak_bytes() -> int:
        try:
            return int(
                (jax.devices()[0].memory_stats() or {}).get(
                    "peak_bytes_in_use", 0
                )
            )
        except Exception:  # noqa: BLE001 — CPU backends have no stats
            return 0

    rows = []
    pool_tokens = engine.runner.num_blocks * config.block_size
    for L in lens:
        rid = f"lc{L}"
        if L + LONGCTX_ANSWER_TOK > pool_tokens:
            rows.append({
                "prompt_tokens": L, "admitted": False,
                "reason": f"KV pool holds {pool_tokens} tokens",
            })
            continue
        prompt = rng.randint(0, vocab, L).tolist()
        snap = engine.stats()
        hbm_hw = 0.0
        ttft = None
        t0 = time.time()
        engine.add_request(rid, prompt_token_ids=prompt,
                           sampling_params=sp)
        while engine.has_unfinished():
            outs = engine.step()
            hbm_hw = max(hbm_hw, engine.block_manager.usage)
            if ttft is None and any(
                o.request_id == rid and o.token_ids for o in outs
            ):
                ttft = time.time() - t0
        e2e = time.time() - t0
        st = engine.stats()
        rows.append({
            "prompt_tokens": L,
            "admitted": True,
            "ttft_s": round(ttft, 3) if ttft is not None else -1,
            "e2e_s": round(e2e, 3),
            # a ring claim that FAILED back to chunked prefill must not
            # pollute the ring-vs-chunked A/B rows as "ring"
            "served_via": (
                "chunked"
                if st.long_prefill_requests_total
                == snap.long_prefill_requests_total
                else "ring"
                if st.long_prefill_fallbacks_total
                == snap.long_prefill_fallbacks_total
                else "ring-fallback"
            ),
            "hbm_highwater_frac": round(hbm_hw, 4),
            "hbm_peak_bytes": _peak_bytes(),
            "phase_s": {
                "ring": round(
                    st.long_prefill_ring_seconds_total
                    - snap.long_prefill_ring_seconds_total, 3),
                "d2h": round(
                    st.long_prefill_d2h_seconds_total
                    - snap.long_prefill_d2h_seconds_total, 3),
                "land": round(
                    st.long_prefill_land_seconds_total
                    - snap.long_prefill_land_seconds_total, 3),
                "overflow": round(
                    st.long_prefill_overflow_seconds_total
                    - snap.long_prefill_overflow_seconds_total, 3),
            },
        })
        print(f"# longctx {L}: {rows[-1]}", file=sys.stderr)
    st = engine.stats()
    served = [r for r in rows if r.get("admitted")]
    result = {
        "metric": (
            f"long-context TTFT sweep ({mc.name}, "
            f"{lens[0]}-{lens[-1]} tok prompts, "
            f"{'ring sp=' + str(LONGCTX_SP) if ring_live else 'chunked'}"
            f", {TP} chip(s))"
        ),
        "value": served[-1]["ttft_s"] if served else -1,
        "unit": f"s_ttft@{served[-1]['prompt_tokens']}tok"
        if served else "s_ttft",
        "vs_baseline": -1,
        "detail": {
            "config_label": label,
            "sweep": rows,
            "long_prefill": {
                "enabled": ring,
                "live": ring_live,
                "sp": LONGCTX_SP if ring_live else 0,
                "threshold": LONGCTX_THRESHOLD if ring_live else None,
                "chunk_tokens": (
                    engine.long_prefill.chunk if ring_live else None
                ),
                "requests": st.long_prefill_requests_total,
                "chunks": st.long_prefill_chunks_total,
                "fallbacks": st.long_prefill_fallbacks_total,
                "phase_s": {
                    "ring": round(st.long_prefill_ring_seconds_total, 3),
                    "d2h": round(st.long_prefill_d2h_seconds_total, 3),
                    "land": round(st.long_prefill_land_seconds_total, 3),
                    "overflow": round(
                        st.long_prefill_overflow_seconds_total, 3),
                },
            },
            "compiles": {
                "total": engine.runner.compile_events_total,
                "by_kind": dict(sorted(
                    engine.runner.compile_events.items()
                )),
            },
        },
    }
    watchdog.cancel()
    engine.shutdown()
    del engine
    gc.collect()
    return result


def run_config(sched_steps: int, prefill_seqs: int, async_decode: bool,
               label: str) -> dict:
    import gc

    import jax  # noqa: F401 — backend already initialized

    if LONGCTX:
        # @longctx replaces the multi-round QA workload with the
        # prompt-length sweep (the base k/pack label still selects the
        # decode config the answers run under)
        return _run_longctx(label)

    watchdog = _arm_watchdog(
        float(os.environ.get("PST_BENCH_RUN_DEADLINE", "1200")),
        f"run_config[{label}]",
    )

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.llm_engine import LLMEngine
    from production_stack_tpu.engine.sampling_params import SamplingParams

    t_setup = time.time()
    # final-round sequence length: round-1 prompt plus per-round growth
    # (answer fed back into the session + the next question)
    final_len = (
        SYSTEM_PROMPT_TOK + HISTORY_TOK
        + (ROUNDS - 1) * (ANSWER_TOK + QUESTION_TOK) + ANSWER_TOK
    )
    # @kvoff: cap the KV pool so finished sessions' prefixes spill into
    # the cpu/disk tiers between rounds while every ACTIVE lane still
    # fits (peak active = NUM_USERS x final_len; 1.15x slack covers the
    # +1 generation block and pinned-export transients)
    kv_blocks = None
    kv_kwargs: dict = {}
    cache_server_box = None
    if KV_OFFLOAD:
        kv_blocks = KV_BLOCKS or int(
            1.15 * NUM_USERS * -(-final_len // 32)
        )
        import shutil

        shutil.rmtree(DISK_OFFLOAD_DIR, ignore_errors=True)
        kv_kwargs = dict(
            num_kv_blocks=kv_blocks,
            cpu_offload_bytes=CPU_OFFLOAD_MB * 2**20,
            disk_offload_dir=DISK_OFFLOAD_DIR,
            sync_kv_offload=SYNC_KV,
        )
        if KV_REMOTE:
            # @remotekv: LMCache-like topology — capped cpu buffer +
            # in-process shared cache server, NO local disk tier
            # (overflow past host RAM restores over the wire as ONE
            # chain pull; write-behind batched PUTs ship every export)
            cache_server_box = _cache_server_box()
            kv_kwargs["disk_offload_dir"] = None
            kv_kwargs["remote_cache_url"] = (
                f"127.0.0.1:{cache_server_box.port}"
            )
    config = EngineConfig(
        model=MODEL,
        tokenizer="byte",
        dtype="bfloat16",
        cache_dtype="bfloat16",
        block_size=32,
        hbm_utilization=0.85,
        **kv_kwargs,
        max_model_len=max(4096, 32 * (-(-(final_len + 64) // 32))),
        max_num_seqs=NUM_USERS,
        max_prefill_chunk=PREFILL_CHUNK,
        max_prefill_seqs=prefill_seqs,
        tensor_parallel_size=TP,
        num_scheduler_steps=sched_steps,
        # elastic fused decode A/B: @noelastic pins the fixed-trip
        # fixed-K control (the pre-elastic behavior) for attribution
        device_stop=ELASTIC,
        adaptive_decode_k=ELASTIC,
        # unified ragged dispatch A/B: @noragged pins the split
        # alternating prefill/decode rounds for attribution
        ragged_dispatch=RAGGED,
        # single-kernel ragged attention A/B: @norpakernel pins the
        # composed per-lane kernels for attribution (pallas impl only)
        ragged_kernel=RAGGED_KERNEL,
        async_decode=async_decode,
        prefetch_decode=PREFETCH,
        prefill_pipeline=PREFILL_PIPELINE,
        # tracing A/B: @trace turns the full recording path on (timeline
        # + memory span exporter); the default control has every hook
        # compiled down to one boolean check
        request_timeline=TRACE,
        tracing_exporter="memory" if TRACE else "none",
        seed=0,
    )
    pd_prefiller = None
    if PD:
        import dataclasses as _dc

        # @pd: a separate prefill-role engine (own step thread + KV
        # transfer server) takes every round-1 prompt at max_tokens=1;
        # the measured decode engine pulls the chain through its
        # PeerTier staged restore. Colocated on the one chip: size the
        # prefill engine's pool small (it only holds in-flight phase-1
        # chains until they are pulled) and leave the decode engine
        # the rest. The prefill engine needs no offload tiers.
        pf_blocks = 4 * max(
            1, -(-(SYSTEM_PROMPT_TOK + HISTORY_TOK) // 32)
        ) * max(2, min(8, NUM_USERS))
        pd_prefiller = _PDPrefiller(_dc.replace(
            config,
            kv_role="prefill",
            hbm_utilization=0.2,
            num_kv_blocks=pf_blocks,
            cpu_offload_bytes=0,
            disk_offload_dir=None,
            request_timeline=False,
            tracing_exporter="none",
        ))
        config = _dc.replace(
            config,
            kv_role="decode",
            kv_transfer_config={
                "peer": f"127.0.0.1:{pd_prefiller.port}"
            },
            hbm_utilization=0.6,
        )
    engine = LLMEngine(config)
    mc = engine.runner.model_config
    print(
        f"# engine up in {time.time() - t_setup:.1f}s on "
        f"{jax.devices()[0].platform}, {engine.runner.num_blocks} KV blocks",
        file=sys.stderr,
    )

    rng = np.random.RandomState(0)
    vocab = mc.vocab_size
    shared_prefix = rng.randint(0, vocab, SYSTEM_PROMPT_TOK).tolist()
    prompts = [
        shared_prefix + rng.randint(0, vocab, HISTORY_TOK).tolist()
        for _ in range(NUM_USERS)
    ]
    # the user's next message for each later round, fixed up front so the
    # workload is deterministic across configs
    questions = [
        [rng.randint(0, vocab, QUESTION_TOK).tolist()
         for _ in range(ROUNDS - 1)]
        for _ in range(NUM_USERS)
    ]
    sp = SamplingParams(
        max_tokens=ANSWER_TOK, temperature=0.0, ignore_eos=True
    )

    # -- warmup: compile the buckets the timed run will hit, so no XLA
    # compile lands inside the measurement: full-length prompts select the
    # same prefill/decode ctx buckets as the real pass
    t0 = time.time()
    if pd_prefiller is not None:
        # compile the prefill engine's full-prompt buckets FIRST, so
        # the decode engine's warmup below pulls real chains — warming
        # the transfer link and the staged-import scatter compile
        # before the timed run
        pd_prefiller.warmup(prompts[:2])
    engine.generate(
        prompts[:2],
        SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True),
    )
    print(f"# warmup/compile {time.time() - t0:.1f}s", file=sys.stderr)

    if PRECOMPILE:
        # compile every prefill program the QPS-paced run can reach so no
        # XLA compile lands inside a TTFT/ITL measurement: lone arrivals
        # take the SINGLE-sequence path (warmup packs its two prompts, so
        # singles would otherwise first compile mid-run), bursts take the
        # packed path at pow2 group sizes, and a fully prefix-cached
        # prompt resumes with a 1-token tail chunk (see
        # ModelRunner.precompile_prefill)
        t0 = time.time()
        rnr = engine.runner
        plen = SYSTEM_PROMPT_TOK + HISTORY_TOK
        chunk = config.max_prefill_chunk
        # walk the actual chunking: each sub-chunk is min(chunk, plen-p)
        # tokens at total p+len — a short FINAL sub-chunk (plen % chunk)
        # lands in its own smaller t_pad bucket and must be precompiled
        # too, or its compile lands inside a live TTFT measurement
        pieces = sorted({
            (min(chunk, plen - p), rnr._ctx_bucket(p + min(chunk, plen - p)))
            for p in range(0, plen, chunk)
        })
        tail_ctx = rnr._ctx_bucket(plen)
        # a fully prefix-cached prompt resumes past the last whole-block
        # boundary, so its tail chunk is plen - floor((plen-1)/bs)*bs
        # tokens (in [1, block_size]) — use the exact length so the tail
        # lands in the same t_pad bucket the timed run will reach
        bs = config.block_size
        tail_len = plen - ((plen - 1) // bs) * bs
        singles = pieces + [(tail_len, tail_ctx)]
        groups = []
        s = 2
        while s <= min(prefill_seqs, NUM_USERS):
            groups += [(s, cl, t) for cl, t in pieces]
            s *= 2
        if prefill_seqs > 1:
            groups.append((2, tail_len, tail_ctx))
        if ROUNDS > 1:
            # rounds 2+ resume from the prefix cache at the last cached
            # whole-block boundary of the previous round's sequence; with
            # greedy + ignore_eos every length is deterministic, so each
            # round's resume tail compiles ahead of the timed run. Fused
            # K-step rounds finish whole lane groups together, so
            # resubmissions arrive in BURSTS — the packed variants of
            # each tail are reachable too. Dedup by bucket so shared
            # (t_pad, c_pad) programs cost one trash dispatch, not one
            # per round.
            seen = {
                (rnr._prefill_bucket(cl), t) for cl, t in singles
            }
            seen_g = {
                (gs, rnr._prefill_bucket(cl), t) for gs, cl, t in groups
            }
            L = plen
            for r in range(ROUNDS - 1):
                prev_total = L + ANSWER_TOK
                L = prev_total + QUESTION_TOK
                cached = (prev_total // bs) * bs
                rtail = L - cached
                cb = rnr._ctx_bucket(L)
                if (rnr._prefill_bucket(rtail), cb) not in seen:
                    seen.add((rnr._prefill_bucket(rtail), cb))
                    singles.append((rtail, cb))
                gs = 2
                while gs <= min(prefill_seqs, NUM_USERS):
                    key = (gs, rnr._prefill_bucket(rtail), cb)
                    if key not in seen_g:
                        seen_g.add(key)
                        groups.append((gs, rtail, cb))
                    gs *= 2
        ndisp = rnr.precompile_prefill(singles, groups)
        if ROUNDS > 1:
            # later rounds also cross decode ctx buckets (pow2 block
            # counts) the warmup never reached; elastic serving also
            # dispatches the pow2 K buckets below the cap (adaptive K)
            # and the prefetch-chained device-stop variant
            grow = ANSWER_TOK + QUESTION_TOK
            decode_ctxs = [
                plen + r * grow + ANSWER_TOK for r in range(ROUNDS)
            ]
            from production_stack_tpu.engine.scheduler import (
                decode_precompile_variants,
            )

            # the ONE variant-selection policy precompile_serving uses
            # too — the warmed (k, chained, stop) set must match what
            # pick_decode_k + the dispatch gates select at runtime
            for kk, chained, stop in decode_precompile_variants(
                sched_steps, ELASTIC,
                overlap=async_decode or PREFETCH,
                async_chained=async_decode,
                device_stop=ELASTIC,
            ):
                ndisp += rnr.precompile_decode(
                    decode_ctxs, kk, chained=chained, stop=stop,
                )
            if RAGGED and not async_decode:
                # mixed rounds here pair resume-tail prefill lanes with
                # decode lanes in the same session-length regime: warm
                # the small lane-mix buckets on the decode-ctx diagonal
                # (resubmission bursts are mostly 1-2 lanes; bigger
                # mixes and off-diagonal ctx pairs compile on first use
                # and are cheap on restart via JAX_COMPILATION_CACHE_DIR)
                from production_stack_tpu.engine.scheduler import (
                    decode_k_buckets,
                )

                ndisp += rnr.precompile_ragged(
                    [max(1, c - sched_steps + 1) for c in decode_ctxs],
                    decode_k_buckets(sched_steps, ELASTIC),
                    min(2, prefill_seqs),
                    PREFILL_CHUNK,
                    stop=ELASTIC,
                    chained=PREFETCH,
                )
        print(
            f"# prefill precompile: {ndisp} dispatches in "
            f"{time.time() - t0:.1f}s",
            file=sys.stderr,
        )

    # -- timed run ---------------------------------------------------------
    # QPS-paced arrivals, like the reference harness (multi-round-qa.py
    # drives a target QPS): TTFT is measured from each request's own
    # arrival, not from the start of a burst
    ttfts: dict[str, float] = {}
    t_start = time.time()
    # request ids are "u<i>:r<round>"; round-1 arrivals are QPS-paced,
    # rounds 2+ resubmit the grown session the moment the previous
    # answer lands (reference sessions chat continuously)
    arrivals = [(f"u{i}:r1", t_start + i / QPS, p)
                for i, p in enumerate(prompts)]
    submit_t: dict[str, float] = {}
    pending = list(arrivals)
    session_prompt = list(prompts)  # per-user, grows each round
    session_round = [1] * NUM_USERS

    gen_tokens = 0
    decode_time = 0.0
    last_token_t: dict[str, float] = {}
    itls: list[float] = []  # inter-token gaps across all streams
    while (pending or engine.has_unfinished()
           or (pd_prefiller is not None and pd_prefiller.busy())):
        now = time.time()
        while pending and pending[0][1] <= now:
            rid, due, p = pending.pop(0)
            if pd_prefiller is not None:
                # @pd: the cold prompt's phase 1 runs on the prefill
                # engine; the decode engine admits it after the chain
                # pull (TTFT still counts from the scheduled arrival —
                # the whole disaggregated path is the measurement)
                pd_prefiller.submit(rid, p)
            else:
                engine.add_request(
                    rid, prompt_token_ids=p, sampling_params=sp
                )
            # TTFT counts from the SCHEDULED arrival: admission delay past
            # `due` is queueing the system caused and must stay in the
            # measurement (avoiding coordinated omission)
            submit_t[rid] = due
        if pd_prefiller is not None:
            for rid, toks in pd_prefiller.drain():
                engine.add_request(
                    rid, prompt_token_ids=toks, sampling_params=sp
                )
        if not engine.has_unfinished():
            if pending:
                time.sleep(
                    max(0.0, min(0.002, pending[0][1] - time.time()))
                    if pd_prefiller is not None
                    else max(0.0, pending[0][1] - time.time())
                )
            elif pd_prefiller is not None:
                time.sleep(0.001)  # phase-1 in flight on the prefiller
            continue
        st = time.time()
        outs = engine.step()
        dt = time.time() - st
        now = time.time()
        for out in outs:
            if out.request_id not in ttfts and out.token_ids:
                ttfts[out.request_id] = now - submit_t[out.request_id]
            if out.new_token_ids:
                prev = last_token_t.get(out.request_id)
                if prev is not None:
                    itls.append(now - prev)
                last_token_t[out.request_id] = now
            if out.finished:
                uid = int(out.request_id.split(":")[0][1:])
                r = session_round[uid]
                if r < ROUNDS:
                    session_prompt[uid] = (
                        session_prompt[uid] + list(out.token_ids)
                        + questions[uid][r - 1]
                    )
                    session_round[uid] = r + 1
                    nrid = f"u{uid}:r{r + 1}"
                    engine.add_request(
                        nrid,
                        prompt_token_ids=session_prompt[uid],
                        sampling_params=sp,
                    )
                    submit_t[nrid] = now
        if engine.last_step_kind in ("decode", "ragged"):
            # ragged rounds generate decode tokens too; their wall time
            # includes the fused prefill lanes BY DESIGN (the unified
            # round is the thing being measured)
            gen_tokens += sum(len(o.new_token_ids) for o in outs)
            decode_time += dt
    total_time = time.time() - t_start

    all_gen = NUM_USERS * ANSWER_TOK * ROUNDS
    decode_tps = gen_tokens / decode_time if decode_time > 0 else 0.0
    overall_tps = all_gen / total_time
    ttft_arr = np.asarray(sorted(ttfts.values()))
    p50_ttft = float(np.percentile(ttft_arr, 50)) if len(ttft_arr) else -1
    itl_arr = np.asarray(itls)
    itl_p = (
        {
            "p50_itl_s": round(float(np.percentile(itl_arr, 50)), 4),
            "p90_itl_s": round(float(np.percentile(itl_arr, 90)), 4),
            "p99_itl_s": round(float(np.percentile(itl_arr, 99)), 4),
        }
        if len(itl_arr)
        else {}
    )

    model_bytes = mc.num_params() * 2  # bf16
    # each of the TP chips holds model_bytes/TP and streams it per decode
    # step at HBM_BW, so the aggregate roofline scales with TP; reported
    # value and vs_baseline are both per-chip so TP runs stay comparable
    roofline_tps = NUM_USERS * TP * HBM_BW_GBPS * 1e9 / model_bytes

    r1 = np.asarray(
        [v for k, v in ttfts.items() if k.endswith(":r1")]
    )
    resume = np.asarray(
        [v for k, v in ttfts.items() if not k.endswith(":r1")]
    )
    result = {
        "metric": (
            f"multi-round-qa-style serving throughput "
            f"({mc.name}, {NUM_USERS} users x {ROUNDS} rounds, "
            f"{SYSTEM_PROMPT_TOK}+{HISTORY_TOK} tok prompts, "
            f"{ANSWER_TOK} tok answers, {TP} chip(s))"
        ),
        "value": round(overall_tps / TP, 1),
        "unit": "gen_tokens/s/chip",
        "vs_baseline": round(decode_tps / roofline_tps, 3),
        "detail": {
            "tensor_parallel_size": TP,
            "arrival_qps": QPS,
            "num_scheduler_steps": sched_steps,
            "prefill_seqs": prefill_seqs,
            "async_decode": async_decode,
            "prefetch_decode": PREFETCH,
            "prefill_pipeline": PREFILL_PIPELINE,
            "trace": TRACE,
            "config_label": label,
            "rounds": ROUNDS,
            "decode_tokens_per_s_aggregate": round(decode_tps, 1),
            "p50_ttft_s": round(p50_ttft, 3),
            # round-1 TTFT pays the full prefill; rounds 2+ resume from
            # the prefix cache and re-prefill only the session tail
            "p50_ttft_round1_s": round(
                float(np.percentile(r1, 50)), 3
            ) if len(r1) else -1,
            "p50_ttft_resume_s": round(
                float(np.percentile(resume, 50)), 3
            ) if len(resume) else -1,
            "preemptions": engine.stats().num_preemptions_total,
            # h2d-prefetch effectiveness: hits dispatched on a staged
            # buffer (no serial upload); misses staged but invalidated
            "staged_hits": engine._staged_hits_total,
            "staged_misses": engine._staged_misses_total,
            # pipelined-prefill attribution: where prefill wall time
            # went (prep / h2d / dispatch / fetch) + staging and
            # cold-prompt chaining effectiveness
            "prefill_phase_s": {
                k: round(v, 3)
                for k, v in engine.runner.prefill_phase_s.items()
            },
            # per-phase sample counts: phase_s / phase_n = mean wall
            # time per dispatch for that phase
            "prefill_phase_n": dict(engine.runner.prefill_phase_n),
            "prefill_staged_hits": engine._pf_staged_hits_total,
            "prefill_staged_misses": engine._pf_staged_misses_total,
            "prefill_chained_chunks": engine._pf_chained_chunks_total,
            # elastic fused decode attribution: chosen-K distribution
            # (adaptive sizing), host-discarded overshoot slots (the
            # K=32 waste mode — ~0 under device stops), and whole-round
            # device early exits
            "elastic_decode": {
                "device_stop": ELASTIC,
                "adaptive_decode_k": ELASTIC,
                "decode_rounds": engine._decode_rounds_total,
                "decode_k_hist": {
                    str(kk): v
                    for kk, v in sorted(engine._decode_k_hist.items())
                },
                "overshoot_tokens":
                    engine._decode_overshoot_tokens_total,
                "early_exit_rounds":
                    engine._decode_early_exit_rounds_total,
            },
            # unified ragged dispatch attribution (@ragged/@noragged):
            # fused lane-typed rounds, their lane-mix distribution
            # ("p<prefill>+d<decode>" per fused round), the share of
            # rounds that carried prefill lanes, split-execution
            # fallbacks (exotic lanes), and ragged h2d-staging
            # effectiveness
            "ragged_dispatch": {
                "enabled": RAGGED,
                "ragged_rounds": engine._ragged_rounds_total,
                "split_rounds": engine._ragged_split_rounds_total,
                "lane_mix_hist": dict(sorted(
                    engine._ragged_lane_mix_hist.items()
                )),
                # of all rounds that decoded, how many also carried
                # prefill lanes (ragged rounds tick decode_rounds too)
                "prefill_lane_share": round(
                    engine._ragged_rounds_total
                    / max(1, engine._decode_rounds_total), 3,
                ),
                "staged_hits": engine._ragged_staged_hits_total,
                "staged_misses": engine._ragged_staged_misses_total,
            },
            # compile-count attribution (@rpa/@norpakernel): program-
            # variant builds per builder kind — the cold-start compile
            # tax the single-kernel row-bucket variants shrink. Reads
            # the same counters as tpu:compile_events_total.
            "compiles": {
                "ragged_kernel": RAGGED_KERNEL,
                "total": engine.runner.compile_events_total,
                "by_kind": dict(sorted(
                    engine.runner.compile_events.items()
                )),
            },
            # zero-stall KV tiering attribution (@kvoff): export time is
            # offload-worker wall (overlapped), restore time is
            # enqueue->landed (overlaps queue wait); tier counters show
            # which tier actually served the resumes
            # disaggregated prefill/decode attribution (@pd): phase-1
            # count on the prefill engine, peer pull counters on the
            # decode engine (hits = blocks transferred, fallbacks =
            # failed pulls), staged-restore landings, and what the
            # transfer server actually served
            **({
                "pd_transfer": {
                    "colocated_same_chip": True,
                    "phase1_requests": pd_prefiller.submitted,
                    "peer": engine.kv_peer.counters(),
                    "restore_blocks": engine._kv_restore_blocks_total,
                    "restore_fallbacks":
                        engine._kv_restore_fallbacks_total,
                    "transfer_server": {
                        "chains": pd_prefiller.server.chains_served,
                        "blocks": pd_prefiller.server.blocks_served,
                    },
                },
            } if PD else {}),
            **({
                "kv_offload": {
                    "kv_blocks": kv_blocks,
                    "sync_kv_offload": SYNC_KV,
                    "export_blocks": engine._kv_export_blocks_total,
                    "export_s": round(
                        engine._kv_export_seconds_total, 3),
                    "restore_blocks": engine._kv_restore_blocks_total,
                    "restore_s": round(
                        engine._kv_restore_seconds_total, 3),
                    "restore_fallbacks":
                        engine._kv_restore_fallbacks_total,
                    "export_sync_fallbacks":
                        engine._kv_export_sync_fallbacks_total,
                    "tiers": engine.offload.counters()
                    if engine.offload is not None else {},
                },
            } if KV_OFFLOAD else {}),
            # shared-cache attribution (@remotekv): engine-side
            # RemoteTier counters (write-behind frames shipped, chain
            # pull hits/misses, wire bytes) + the server's own
            # occupancy/hit-rate stats
            **({
                "kv_remote": {
                    "remote": engine.offload.remote.counters()
                    if engine.offload is not None
                    and engine.offload.remote is not None else {},
                    "server": cache_server_box.stats(),
                },
            } if KV_REMOTE and cache_server_box is not None else {}),
            "mean_ttft_s": round(float(ttft_arr.mean()), 3)
            if len(ttft_arr)
            else -1,
            "total_wall_s": round(total_time, 1),
            "roofline_decode_tokens_per_s": round(roofline_tps, 1),
            "prefix_cache_hit_rate": round(
                engine.stats().prefix_cache_hit_rate, 3
            ),
            **itl_p,
        },
    }
    # the measurement is complete: disarm the abort watchdog BEFORE
    # teardown, which can itself block on a dead tunnel — a hung
    # shutdown must not overwrite a successful result with an abort
    # row. Arm a teardown guard instead that EMITS the result and exits
    # cleanly, so the measurement survives a wedged shutdown.
    import threading

    watchdog.cancel()

    def emit_and_exit() -> None:
        print(json.dumps(result), flush=True)
        os._exit(0)

    teardown_guard = threading.Timer(120.0, emit_and_exit)
    teardown_guard.daemon = True
    teardown_guard.start()
    # free the engine (params + KV cache) before the next sweep config
    # allocates its own — two live engines would OOM the chip's HBM
    if pd_prefiller is not None:
        pd_prefiller.close()
        del pd_prefiller
    engine.shutdown()
    del engine
    if cache_server_box is not None:
        cache_server_box.close()
    gc.collect()
    teardown_guard.cancel()
    return result


if __name__ == "__main__":
    main()
