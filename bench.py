"""Benchmark: serving throughput + TTFT on the real TPU chip.

Workload shape follows the reference's multi-round-qa definition scaled to
one chip (reference: benchmarks/multi-round-qa/run.sh — shared system
prompt + long per-user history + ~100-token answers): concurrent sessions
with a shared prefix exercise chunked prefill, prefix caching, continuous
batching, and paged decode together.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is the fraction of the HBM-bandwidth decode roofline achieved
(roofline tok/s = batch * HBM_BW / model_bytes — every decode step must
stream the weights once; the reference repo commits no absolute numbers to
compare against, see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("PST_LOG_LEVEL", "WARNING")  # keep stdout JSON-only

import numpy as np  # noqa: E402

MODEL = os.environ.get("PST_BENCH_MODEL", "llama-3.2-3b")
# north-star config is Llama-3-8B tp=8 on a v5e-8; the driver exposes one
# chip, so the default serves the largest family member that fits it with
# the Pallas kernels engaged (3B, head_dim 128 — the 1B's head_dim 64
# falls back to the XLA path, see engine/model_runner.py).
# On a full slice: PST_BENCH_MODEL=llama-3-8b PST_BENCH_TP=8 python bench.py
TP = int(os.environ.get("PST_BENCH_TP", "1"))
NUM_USERS = int(os.environ.get("PST_BENCH_USERS", "16"))
SYSTEM_PROMPT_TOK = int(os.environ.get("PST_BENCH_SYS_TOK", "512"))
HISTORY_TOK = int(os.environ.get("PST_BENCH_HISTORY_TOK", "1024"))
ANSWER_TOK = int(os.environ.get("PST_BENCH_ANSWER_TOK", "100"))
# fused decode iterations per dispatch (amortises the host<->device RTT,
# which dominates through the tunneled chip; see engine/model_runner.py)
SCHED_STEPS = int(os.environ.get("PST_BENCH_SCHED_STEPS", "8"))
# cross-sequence prefill packing group cap (1 = round-2 behavior)
PREFILL_SEQS = int(os.environ.get("PST_BENCH_PREFILL_SEQS", "8"))
# double-buffered decode dispatch (0 = synchronous fetch per round)
ASYNC_DECODE = os.environ.get("PST_BENCH_ASYNC", "1") == "1"
# pre-compile the packed-prefill buckets the timed run will hit so no
# XLA compile lands inside a TTFT measurement (each tunnel compile is
# tens of seconds)
PRECOMPILE = os.environ.get("PST_BENCH_PRECOMPILE", "1") == "1"
HBM_BW_GBPS = float(os.environ.get("PST_BENCH_HBM_BW", "819"))  # v5e
QPS = float(os.environ.get("PST_BENCH_QPS", "2.0"))  # arrival pacing


def _init_backend_or_die(timeout_s: float = 60.0, retries: int = 1):
    """Initialize the jax backend with a hard deadline.

    Round-1 lesson: `jax.devices()` can hang indefinitely when the TPU
    backend is unreachable, leaving the driver to kill the process with no
    diagnostic. Probe backend init in a daemon thread with a bounded wait;
    on failure emit the ONE JSON line the driver records (with an `error`
    field) and exit non-zero fast.
    """
    import threading

    err = "unknown"
    for attempt in range(retries + 1):
        box: dict = {}

        def probe() -> None:
            try:
                import jax

                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 - report any init failure
                box["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            # a hung probe still holds the import/backend-init lock, so a
            # retry would block on the same state — abort immediately
            err = f"jax backend init timed out after {timeout_s:.0f}s"
            print(f"# backend init: {err}", file=sys.stderr)
            break
        if "error" in box:
            err = box["error"]
        else:
            return box["devices"]
        print(f"# backend init attempt {attempt + 1} failed: {err}",
              file=sys.stderr)
    print(json.dumps({
        "metric": "bench-aborted: jax backend unavailable",
        "value": 0.0,
        "unit": "gen_tokens/s/chip",
        "vs_baseline": 0.0,
        "error": err,
    }))
    sys.exit(1)


def main() -> None:
    # chip-session hygiene: one TPU process at a time, SIGTERM-only stop
    from production_stack_tpu.utils import chip_guard
    from production_stack_tpu.utils.chip_guard import ChipBusyError

    try:
        _chip_lock = chip_guard.engage()  # noqa: F841 — held for run life
    except ChipBusyError as e:
        print(f"# {e}", file=sys.stderr)
        print(json.dumps({
            "metric": "bench-aborted: chip lock held by another process",
            "value": 0.0,
            "unit": "gen_tokens/s/chip",
            "vs_baseline": 0.0,
            "error": str(e)[:200],
        }))
        sys.exit(1)
    devices = _init_backend_or_die()
    import jax

    print(f"# backend: {devices[0].platform} x{len(devices)}",
          file=sys.stderr)

    if os.environ.get("PST_BENCH_SWEEP", "0") == "1":
        _run_sweep()
    else:
        print(json.dumps(run_config(
            SCHED_STEPS, PREFILL_SEQS, ASYNC_DECODE, "default"
        )))


def _run_sweep() -> None:
    """One chip session, the full measurement matrix: K=1 control, K=8,
    packing on/off, async on/off. Results stream into BENCH_SWEEP.json
    after EVERY config so a mid-sweep wedge still leaves evidence; the
    best row is the driver-contract stdout line."""
    configs = [
        ("k1-sync-nopack", 1, 1, False),
        (f"k{SCHED_STEPS}-sync-nopack", SCHED_STEPS, 1, False),
        (f"k{SCHED_STEPS}-sync-packed", SCHED_STEPS, PREFILL_SEQS, False),
        (f"k{SCHED_STEPS}-async-packed", SCHED_STEPS, PREFILL_SEQS, True),
    ]
    out_path = os.environ.get("PST_BENCH_SWEEP_OUT", "BENCH_SWEEP.json")
    results: list[dict] = []
    for label, k, ps, ad in configs:
        try:
            r = run_config(k, ps, ad, label)
        except Exception as e:  # noqa: BLE001 — record, keep sweeping
            r = {"metric": f"sweep-config-failed: {label}", "value": 0.0,
                 "unit": "gen_tokens/s/chip", "vs_baseline": 0.0,
                 "error": f"{type(e).__name__}: {e}"[:300]}
        print(f"# sweep {label}: {json.dumps(r)}", file=sys.stderr)
        results.append(r)
        with open(out_path, "w") as f:
            json.dump({"ts": time.strftime("%FT%TZ", time.gmtime()),
                       "model": MODEL, "results": results}, f, indent=1)
    best = max(results, key=lambda r: r.get("value", 0.0))
    print(json.dumps(best))


def run_config(sched_steps: int, prefill_seqs: int, async_decode: bool,
               label: str) -> dict:
    import gc

    import jax  # noqa: F401 — backend already initialized

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.llm_engine import LLMEngine
    from production_stack_tpu.engine.sampling_params import SamplingParams

    t_setup = time.time()
    config = EngineConfig(
        model=MODEL,
        tokenizer="byte",
        dtype="bfloat16",
        cache_dtype="bfloat16",
        block_size=32,
        hbm_utilization=0.85,
        max_model_len=4096,
        max_num_seqs=NUM_USERS,
        max_prefill_chunk=512,
        max_prefill_seqs=prefill_seqs,
        tensor_parallel_size=TP,
        num_scheduler_steps=sched_steps,
        async_decode=async_decode,
        seed=0,
    )
    engine = LLMEngine(config)
    mc = engine.runner.model_config
    print(
        f"# engine up in {time.time() - t_setup:.1f}s on "
        f"{jax.devices()[0].platform}, {engine.runner.num_blocks} KV blocks",
        file=sys.stderr,
    )

    rng = np.random.RandomState(0)
    vocab = mc.vocab_size
    shared_prefix = rng.randint(0, vocab, SYSTEM_PROMPT_TOK).tolist()
    prompts = [
        shared_prefix + rng.randint(0, vocab, HISTORY_TOK).tolist()
        for _ in range(NUM_USERS)
    ]
    sp = SamplingParams(
        max_tokens=ANSWER_TOK, temperature=0.0, ignore_eos=True
    )

    # -- warmup: compile the buckets the timed run will hit, so no XLA
    # compile lands inside the measurement: full-length prompts select the
    # same prefill/decode ctx buckets as the real pass
    t0 = time.time()
    engine.generate(
        prompts[:2],
        SamplingParams(max_tokens=4, temperature=0.0, ignore_eos=True),
    )
    print(f"# warmup/compile {time.time() - t0:.1f}s", file=sys.stderr)

    if PRECOMPILE and prefill_seqs > 1:
        # sweep the packed-prefill (group, ctx) buckets the QPS-paced run
        # can form (chunks are all max_prefill_chunk long; group sizes
        # bucket to powers of two). Synthetic chunks write into
        # unallocated high blocks: nothing reads them, and real prefills
        # own their blocks exclusively.
        t0 = time.time()
        chunk_len = 512
        nb = engine.runner.num_blocks
        bs = config.block_size
        blocks_per = 2048 // bs
        max_sweep = min(prefill_seqs, NUM_USERS)
        # the sweep claims the TOP max_sweep*blocks_per block ids; the
        # allocator hands out low ids first, so require the pool to be at
        # least twice the swept range (plus warmup's prefix blocks) or
        # skip — overwriting live cached K/V would corrupt the timed run
        if nb < 2 * max_sweep * blocks_per + 64:
            print(
                f"# packed-prefill precompile skipped: pool {nb} blocks "
                f"too small for a {max_sweep}x{blocks_per}-block sweep",
                file=sys.stderr,
            )
            max_sweep = 0
        s = 2
        while s <= max_sweep:
            for total in (512, 1024, 2048):
                start = total - chunk_len
                tabs = []
                for i in range(s):
                    first = nb - (i + 1) * blocks_per
                    tabs.append(
                        list(range(first, first + (total + bs - 1) // bs))
                    )
                engine.runner.prefill_batch(
                    [[1] * chunk_len] * s,
                    start_positions=[start] * s,
                    block_tables=tabs,
                    total_lens=[total] * s,
                )
            s *= 2
        print(
            f"# packed-prefill precompile {time.time() - t0:.1f}s",
            file=sys.stderr,
        )

    # -- timed run ---------------------------------------------------------
    # QPS-paced arrivals, like the reference harness (multi-round-qa.py
    # drives a target QPS): TTFT is measured from each request's own
    # arrival, not from the start of a burst
    ttfts: dict[str, float] = {}
    t_start = time.time()
    arrivals = [(f"u{i}", t_start + i / QPS, p)
                for i, p in enumerate(prompts)]
    submit_t: dict[str, float] = {}
    pending = list(arrivals)

    gen_tokens = 0
    decode_time = 0.0
    last_token_t: dict[str, float] = {}
    itls: list[float] = []  # inter-token gaps across all streams
    while pending or engine.has_unfinished():
        now = time.time()
        while pending and pending[0][1] <= now:
            rid, due, p = pending.pop(0)
            engine.add_request(rid, prompt_token_ids=p, sampling_params=sp)
            # TTFT counts from the SCHEDULED arrival: admission delay past
            # `due` is queueing the system caused and must stay in the
            # measurement (avoiding coordinated omission)
            submit_t[rid] = due
        if not engine.has_unfinished():
            if pending:
                time.sleep(max(0.0, pending[0][1] - time.time()))
            continue
        st = time.time()
        outs = engine.step()
        dt = time.time() - st
        now = time.time()
        for out in outs:
            if out.request_id not in ttfts and out.token_ids:
                ttfts[out.request_id] = now - submit_t[out.request_id]
            if out.new_token_ids:
                prev = last_token_t.get(out.request_id)
                if prev is not None:
                    itls.append(now - prev)
                last_token_t[out.request_id] = now
        if engine.last_step_kind == "decode":
            gen_tokens += sum(len(o.new_token_ids) for o in outs)
            decode_time += dt
    total_time = time.time() - t_start

    all_gen = NUM_USERS * ANSWER_TOK
    decode_tps = gen_tokens / decode_time if decode_time > 0 else 0.0
    overall_tps = all_gen / total_time
    ttft_arr = np.asarray(sorted(ttfts.values()))
    p50_ttft = float(np.percentile(ttft_arr, 50)) if len(ttft_arr) else -1
    itl_arr = np.asarray(itls)
    itl_p = (
        {
            "p50_itl_s": round(float(np.percentile(itl_arr, 50)), 4),
            "p90_itl_s": round(float(np.percentile(itl_arr, 90)), 4),
            "p99_itl_s": round(float(np.percentile(itl_arr, 99)), 4),
        }
        if len(itl_arr)
        else {}
    )

    model_bytes = mc.num_params() * 2  # bf16
    # each of the TP chips holds model_bytes/TP and streams it per decode
    # step at HBM_BW, so the aggregate roofline scales with TP; reported
    # value and vs_baseline are both per-chip so TP runs stay comparable
    roofline_tps = NUM_USERS * TP * HBM_BW_GBPS * 1e9 / model_bytes

    result = {
        "metric": (
            f"multi-round-qa-style serving throughput "
            f"({mc.name}, {NUM_USERS} users, "
            f"{SYSTEM_PROMPT_TOK}+{HISTORY_TOK} tok prompts, "
            f"{ANSWER_TOK} tok answers, {TP} chip(s))"
        ),
        "value": round(overall_tps / TP, 1),
        "unit": "gen_tokens/s/chip",
        "vs_baseline": round(decode_tps / roofline_tps, 3),
        "detail": {
            "tensor_parallel_size": TP,
            "arrival_qps": QPS,
            "num_scheduler_steps": sched_steps,
            "prefill_seqs": prefill_seqs,
            "async_decode": async_decode,
            "config_label": label,
            "decode_tokens_per_s_aggregate": round(decode_tps, 1),
            "p50_ttft_s": round(p50_ttft, 3),
            "mean_ttft_s": round(float(ttft_arr.mean()), 3)
            if len(ttft_arr)
            else -1,
            "total_wall_s": round(total_time, 1),
            "roofline_decode_tokens_per_s": round(roofline_tps, 1),
            "prefix_cache_hit_rate": round(
                engine.stats().prefix_cache_hit_rate, 3
            ),
            **itl_p,
        },
    }
    # free the engine (params + KV cache) before the next sweep config
    # allocates its own — two live engines would OOM the chip's HBM
    engine.shutdown()
    del engine
    gc.collect()
    return result


if __name__ == "__main__":
    main()
