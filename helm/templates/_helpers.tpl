{{/* Common naming + label helpers (role of reference helm/templates/_helpers.tpl) */}}

{{- define "pst.fullname" -}}
{{- .Release.Name | trunc 50 | trimSuffix "-" -}}
{{- end -}}

{{- define "pst.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
{{- end -}}

{{- define "pst.engineLabels" -}}
{{ include "pst.labels" . }}
{{- with .Values.servingEngineSpec.labels }}
{{ toYaml . }}
{{- end }}
{{- end -}}

{{- define "pst.routerLabels" -}}
{{ include "pst.labels" . }}
{{- with .Values.routerSpec.labels }}
{{ toYaml . }}
{{- end }}
{{- end -}}

{{/* HF token secret name: generated unless an existing secret is referenced */}}
{{- define "pst.hfTokenSecretName" -}}
{{- $t := .Values.servingEngineSpec.hfToken -}}
{{- if and $t (kindIs "map" $t) -}}
{{- $t.secretName -}}
{{- else -}}
{{- printf "%s-secrets" (include "pst.fullname" .) -}}
{{- end -}}
{{- end -}}

{{- define "pst.hfTokenSecretKey" -}}
{{- $t := .Values.servingEngineSpec.hfToken -}}
{{- if and $t (kindIs "map" $t) -}}
{{- $t.secretKey -}}
{{- else -}}
hf-token
{{- end -}}
{{- end -}}
