{{/* Common naming + label helpers (role of reference helm/templates/_helpers.tpl) */}}

{{- define "pst.fullname" -}}
{{- .Release.Name | trunc 50 | trimSuffix "-" -}}
{{- end -}}

{{- define "pst.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
{{- end -}}

{{- define "pst.engineLabels" -}}
{{ include "pst.labels" . }}
{{- with .Values.servingEngineSpec.labels }}
{{ toYaml . }}
{{- end }}
{{- end -}}

{{- define "pst.routerLabels" -}}
{{ include "pst.labels" . }}
{{- with .Values.routerSpec.labels }}
{{ toYaml . }}
{{- end }}
{{- end -}}

{{/* HF token secret name: generated unless an existing secret is referenced */}}
{{- define "pst.hfTokenSecretName" -}}
{{- $t := .Values.servingEngineSpec.hfToken -}}
{{- if and $t (kindIs "map" $t) -}}
{{- $t.secretName -}}
{{- else -}}
{{- printf "%s-secrets" (include "pst.fullname" .) -}}
{{- end -}}
{{- end -}}

{{- define "pst.hfTokenSecretKey" -}}
{{- $t := .Values.servingEngineSpec.hfToken -}}
{{- if and $t (kindIs "map" $t) -}}
{{- $t.secretKey -}}
{{- else -}}
hf-token
{{- end -}}
{{- end -}}

{{/* Pod spec shared by multi-host leader and worker templates: every
host of the slice runs the same engine command; the process id comes
from the LWS worker index and the coordinator is the leader pod's
LWS-provided address. GKE schedules the group onto one multi-host slice
via the TPU nodeSelectors. */}}
{{- define "pst.multihostPodSpec" -}}
{{- $root := .root -}}
{{- $ms := .ms -}}
{{- $leader := .leader -}}
nodeSelector:
  cloud.google.com/gke-tpu-accelerator: {{ $ms.tpuAccelerator | default "tpu-v5-lite-podslice" }}
  cloud.google.com/gke-tpu-topology: {{ $ms.tpuTopology | quote }}
  {{- with $ms.nodeSelector }}
  {{- toYaml . | nindent 2 }}
  {{- end }}
{{- with $ms.tolerations }}
tolerations: {{ toYaml . | nindent 2 }}
{{- end }}
containers:
  - name: engine
    image: "{{ $ms.image.repository }}:{{ $ms.image.tag | default "latest" }}"
    command: ["python", "-m", "production_stack_tpu.engine"]
    args:
      - "--model"
      - {{ $ms.modelURL | quote }}
      - "--host"
      - "0.0.0.0"
      - "--port"
      - {{ $root.Values.servingEngineSpec.containerPort | default 8000 | quote }}
      - "--multihost"
      - "--coordinator-address"
      - "$(LWS_LEADER_ADDRESS):{{ ($ms.multiHost).coordinatorPort | default 10001 }}"
      - "--num-processes"
      - {{ $ms.multiHost.hosts | quote }}
      - "--process-id"
      - "$(LWS_WORKER_INDEX)"
      {{- if $ms.tensorParallelSize }}
      - "--tensor-parallel-size"
      - {{ $ms.tensorParallelSize | quote }}
      {{- end }}
      {{- if $ms.maxModelLen }}
      - "--max-model-len"
      - {{ $ms.maxModelLen | quote }}
      {{- end }}
      {{- range $arg := $ms.extraArgs }}
      - {{ $arg | quote }}
      {{- end }}
    env:
      - name: LWS_WORKER_INDEX
        valueFrom:
          fieldRef:
            fieldPath: metadata.labels['leaderworkerset.sigs.k8s.io/worker-index']
      {{- if $root.Values.servingEngineSpec.hfToken }}
      - name: HF_TOKEN
        valueFrom:
          secretKeyRef:
            name: {{ include "pst.hfTokenSecretName" $root }}
            key: {{ include "pst.hfTokenSecretKey" $root }}
      {{- end }}
    ports:
      - containerPort: {{ $root.Values.servingEngineSpec.containerPort | default 8000 }}
      - containerPort: {{ ($ms.multiHost).coordinatorPort | default 10001 }}
    resources:
      requests:
        google.com/tpu: {{ $ms.multiHost.tpuPerHost | default 4 | quote }}
        {{- with ($ms.resources).requests }}
        {{- range $k, $v := . }}
        {{ $k }}: {{ $v | quote }}
        {{- end }}
        {{- end }}
      limits:
        google.com/tpu: {{ $ms.multiHost.tpuPerHost | default 4 | quote }}
    {{- if $leader }}
    startupProbe:
      httpGet: {path: /health, port: {{ $root.Values.servingEngineSpec.containerPort | default 8000 }}}
      failureThreshold: 120
      periodSeconds: 10
    {{- end }}
{{- end -}}
