// production-stack-tpu operator: reconciles TPURuntime / TPURouter /
// LoraAdapter / CacheServer CRs into Deployments, Services, and engine
// LoRA hot-loads.
//
// Role-equivalent of the reference's Go controller-runtime manager
// (reference: operator/cmd/main.go:181-208 — manager with leader
// election, health probes, metrics). Design differences, on purpose:
// - Speaks plain HTTP to a `kubectl proxy` sidecar (no TLS stack in the
//   image); the pod spec pairs this binary with the proxy container.
// - Level-triggered resync loop + watch wake-ups instead of per-resource
//   work queues: at stack scale (tens of CRs) a full resync is cheap and
//   self-healing.
// - Leader election via a Lease object (simple renew loop).
#include <unistd.h>

#include <chrono>
#include <csignal>
#include <cstring>
#include <string>
#include <thread>

#include "controllers.hpp"

using pstjson::Json;
using pstkube::KubeClient;

static volatile sig_atomic_t g_stop = 0;
static void on_signal(int) { g_stop = 1; }

struct Options {
  std::string host = "127.0.0.1";  // kubectl proxy sidecar
  int port = 8001;
  std::string ns = "default";
  int resync_seconds = 10;
  int engine_port = 8000;
  bool once = false;         // single reconcile pass (tests)
  bool leader_elect = false;  // Lease-based election (multi-replica)
};

// ---- Lease leader election (role of controller-runtime's
// leaderelection.LeaderElector in the reference manager,
// reference: operator/cmd/main.go LeaderElection options). One Lease
// object in the managed namespace; the holder renews every resync tick,
// non-holders take over when renewTime goes stale past the duration. ----

static std::string now_rfc3339_micro() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  struct tm tmv;
  gmtime_r(&ts.tv_sec, &tmv);
  char date[32], out[64];
  strftime(date, sizeof(date), "%Y-%m-%dT%H:%M:%S", &tmv);
  snprintf(out, sizeof(out), "%s.%06ldZ", date, ts.tv_nsec / 1000);
  return out;
}

static time_t parse_rfc3339(const std::string& s) {
  struct tm tmv {};
  if (!strptime(s.c_str(), "%Y-%m-%dT%H:%M:%S", &tmv)) return 0;
  return timegm(&tmv);
}

class LeaderElector {
 public:
  LeaderElector(KubeClient& kube, std::string ns, int lease_seconds = 30)
      : kube_(kube), ns_(std::move(ns)), lease_seconds_(lease_seconds) {
    char host[256] = "pst-operator";
    gethostname(host, sizeof(host) - 1);
    id_ = std::string(host) + "-" + std::to_string(getpid());
  }

  // Returns true iff this process holds the lease after the call.
  bool acquire_or_renew() {
    try {
      auto existing = kube_.get(pstkube::kLeases, ns_, kName);
      if (!existing) {
        kube_.create(pstkube::kLeases, ns_, desired(/*acquire=*/true));
        pstop::log("leader election: acquired lease as " + id_);
        return true;
      }
      const auto& spec = existing->get("spec");
      const std::string holder = spec.get("holderIdentity").as_string();
      if (holder == id_) {
        kube_.merge_patch(pstkube::kLeases, ns_, kName,
                          desired(/*acquire=*/false));
        return true;
      }
      const time_t renewed = parse_rfc3339(spec.get("renewTime").as_string());
      const int duration =
          (int)spec.get("leaseDurationSeconds").as_int(lease_seconds_);
      if (renewed != 0 && time(nullptr) - renewed <= duration)
        return false;  // someone else holds a fresh lease
      // Takeover via PUT carrying the observed resourceVersion: if another
      // candidate won the race first, the apiserver rejects this write
      // (409) and we stay follower until the next tick.
      Json takeover = desired(/*acquire=*/true);
      takeover["metadata"] = (*existing).get("metadata");
      kube_.update(pstkube::kLeases, ns_, kName, takeover);
      pstop::log("leader election: took over stale lease from " + holder);
      return true;
    } catch (const std::exception& e) {
      // apiserver hiccup (or conflicting create): act as non-leader; a
      // later tick retries
      pstop::log(std::string("leader election error: ") + e.what());
      return false;
    }
  }

 private:
  static constexpr const char* kName = "pst-operator-leader";

  Json desired(bool acquire) const {
    Json lease = Json::object();
    lease["apiVersion"] = std::string("coordination.k8s.io/v1");
    lease["kind"] = std::string("Lease");
    lease["metadata"]["name"] = std::string(kName);
    Json& spec = lease["spec"];
    spec["holderIdentity"] = id_;
    spec["leaseDurationSeconds"] = (double)lease_seconds_;
    spec["renewTime"] = now_rfc3339_micro();
    if (acquire) spec["acquireTime"] = now_rfc3339_micro();
    return lease;
  }

  KubeClient& kube_;
  std::string ns_;
  std::string id_;
  int lease_seconds_;
};

static Options parse_args(int argc, char** argv) {
  Options o;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        fprintf(stderr, "missing value for %s\n", a.c_str());
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--apiserver-host") o.host = next();
    else if (a == "--apiserver-port") o.port = std::stoi(next());
    else if (a == "--namespace") o.ns = next();
    else if (a == "--resync-seconds") o.resync_seconds = std::stoi(next());
    else if (a == "--engine-port") o.engine_port = std::stoi(next());
    else if (a == "--once") o.once = true;
    else if (a == "--leader-elect") o.leader_elect = true;
    else if (a == "--help" || a == "-h") {
      printf(
          "production-stack-tpu operator\n"
          "  --apiserver-host H   kube-apiserver (kubectl proxy) host "
          "[127.0.0.1]\n"
          "  --apiserver-port P   [8001]\n"
          "  --namespace NS       namespace to manage [default]\n"
          "  --resync-seconds S   full resync interval [10]\n"
          "  --engine-port P      engine pod HTTP port for LoRA calls "
          "[8000]\n"
          "  --once               one reconcile pass, then exit\n"
          "  --leader-elect       Lease-based leader election "
          "(multi-replica)\n");
      exit(0);
    } else {
      fprintf(stderr, "unknown flag %s\n", a.c_str());
      exit(2);
    }
  }
  return o;
}

static void reconcile_all(KubeClient& kube, const Options& o) {
  for (const auto& cr : kube.list(pstkube::kTPURuntimes, o.ns)) {
    try {
      pstop::reconcile_tpuruntime(kube, o.ns, cr);
    } catch (const std::exception& e) {
      pstop::log(std::string("tpuruntime reconcile error: ") + e.what());
    }
  }
  for (const auto& cr : kube.list(pstkube::kTPURouters, o.ns)) {
    try {
      pstop::reconcile_tpurouter(kube, o.ns, cr);
    } catch (const std::exception& e) {
      pstop::log(std::string("tpurouter reconcile error: ") + e.what());
    }
  }
  for (const auto& cr : kube.list(pstkube::kCacheServers, o.ns)) {
    try {
      pstop::reconcile_cacheserver(kube, o.ns, cr);
    } catch (const std::exception& e) {
      pstop::log(std::string("cacheserver reconcile error: ") + e.what());
    }
  }
  for (const auto& cr : kube.list(pstkube::kLoraAdapters, o.ns)) {
    try {
      pstop::reconcile_loraadapter(kube, o.ns, cr, o.engine_port);
    } catch (const std::exception& e) {
      pstop::log(std::string("loraadapter reconcile error: ") + e.what());
    }
  }
}

int main(int argc, char** argv) {
  Options o = parse_args(argc, argv);
  std::signal(SIGINT, on_signal);
  std::signal(SIGTERM, on_signal);
  KubeClient kube(o.host, o.port);
  pstop::log("managing namespace '" + o.ns + "' via " + o.host + ":" +
             std::to_string(o.port));

  if (o.once) {
    reconcile_all(kube, o);
    return 0;
  }

  LeaderElector elector(kube, o.ns);
  bool was_leader = false;
  while (!g_stop) {
    auto t0 = std::chrono::steady_clock::now();
    const bool is_leader = !o.leader_elect || elector.acquire_or_renew();
    if (is_leader != was_leader)
      pstop::log(is_leader ? "became leader" : "lost leadership");
    was_leader = is_leader;
    if (is_leader) {
      try {
        reconcile_all(kube, o);
      } catch (const std::exception& e) {
        pstop::log(std::string("resync error: ") + e.what());
      }
    }
    // wake early on CR changes: a bounded watch doubles as the sleep
    try {
      kube.watch(
          pstkube::kTPURuntimes, o.ns,
          [&](const Json&) { return false; /* any event -> resync */ },
          o.resync_seconds);
    } catch (const std::exception&) {
      // watch unsupported (fake apiserver) or timed out: plain sleep for
      // the remainder of the resync interval
      auto elapsed = std::chrono::duration_cast<std::chrono::seconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
      if (elapsed < o.resync_seconds && !g_stop)
        std::this_thread::sleep_for(
            std::chrono::seconds(o.resync_seconds - elapsed));
    }
  }
  pstop::log("shutting down");
  return 0;
}
