// Kubernetes REST client: typed-enough CRUD over group/version/plural
// paths + watch streaming. Role-equivalent of the reference operator's
// controller-runtime client (reference: operator/cmd/main.go:181-208
// builds a manager; our loop lives in main.cpp).
#pragma once

#include <functional>
#include <optional>
#include <string>
#include <vector>

#include "http.hpp"
#include "json.hpp"

namespace pstkube {

using pstjson::Json;

struct GVR {
  std::string group;    // "" for core
  std::string version;  // "v1", "v1alpha1"
  std::string plural;   // "pods", "tpuruntimes"

  std::string prefix() const {
    if (group.empty()) return "/api/" + version;
    return "/apis/" + group + "/" + version;
  }
};

inline const GVR kPods{"", "v1", "pods"};
inline const GVR kServices{"", "v1", "services"};
inline const GVR kDeployments{"apps", "v1", "deployments"};
inline const GVR kTPURuntimes{"production-stack.tpu", "v1alpha1",
                              "tpuruntimes"};
inline const GVR kTPURouters{"production-stack.tpu", "v1alpha1",
                             "tpurouters"};
inline const GVR kLoraAdapters{"production-stack.tpu", "v1alpha1",
                               "loraadapters"};
inline const GVR kCacheServers{"production-stack.tpu", "v1alpha1",
                               "cacheservers"};
inline const GVR kLeases{"coordination.k8s.io", "v1", "leases"};

class KubeClient {
 public:
  KubeClient(std::string host, int port) : http_(std::move(host), port) {}

  std::string ns_path(const GVR& gvr, const std::string& ns) const {
    return gvr.prefix() + "/namespaces/" + ns + "/" + gvr.plural;
  }

  std::vector<Json> list(const GVR& gvr, const std::string& ns,
                         const std::string& label_selector = "") {
    std::string path = ns_path(gvr, ns);
    if (!label_selector.empty())
      path += "?labelSelector=" + url_encode(label_selector);
    auto r = http_.get(path);
    if (r.status == 404) return {};
    if (r.status >= 300)
      throw psthttp::HttpError("list " + gvr.plural + ": " +
                               std::to_string(r.status));
    // keep the parsed document alive while iterating: the range-for does
    // NOT lifetime-extend a temporary reached through get()/elements()
    Json parsed = Json::parse(r.body);
    std::vector<Json> out;
    for (const auto& item : parsed.get("items").elements())
      out.push_back(item);
    return out;
  }

  std::optional<Json> get(const GVR& gvr, const std::string& ns,
                          const std::string& name) {
    auto r = http_.get(ns_path(gvr, ns) + "/" + name);
    if (r.status == 404) return std::nullopt;
    if (r.status >= 300)
      throw psthttp::HttpError("get " + name + ": " +
                               std::to_string(r.status));
    return Json::parse(r.body);
  }

  Json create(const GVR& gvr, const std::string& ns, const Json& obj) {
    auto r = http_.post(ns_path(gvr, ns), obj.dump());
    if (r.status >= 300)
      throw psthttp::HttpError("create " + gvr.plural + ": " +
                               std::to_string(r.status) + " " + r.body);
    return Json::parse(r.body);
  }

  Json update(const GVR& gvr, const std::string& ns,
              const std::string& name, const Json& obj) {
    auto r = http_.put(ns_path(gvr, ns) + "/" + name, obj.dump());
    if (r.status >= 300)
      throw psthttp::HttpError("update " + name + ": " +
                               std::to_string(r.status) + " " + r.body);
    return Json::parse(r.body);
  }

  Json merge_patch(const GVR& gvr, const std::string& ns,
                   const std::string& name, const Json& patch) {
    auto r = http_.patch(ns_path(gvr, ns) + "/" + name, patch.dump());
    if (r.status >= 300)
      throw psthttp::HttpError("patch " + name + ": " +
                               std::to_string(r.status) + " " + r.body);
    return Json::parse(r.body);
  }

  Json patch_status(const GVR& gvr, const std::string& ns,
                    const std::string& name, const Json& status) {
    Json patch = Json::object();
    patch["status"] = status;
    auto r = http_.patch(ns_path(gvr, ns) + "/" + name + "/status",
                         patch.dump());
    if (r.status == 404 || r.status == 405) {
      // status subresource not enabled (e.g. fake apiserver): merge into
      // the main resource instead
      return merge_patch(gvr, ns, name, patch);
    }
    if (r.status >= 300)
      throw psthttp::HttpError("patch status " + name + ": " +
                               std::to_string(r.status));
    return Json::parse(r.body);
  }

  void remove(const GVR& gvr, const std::string& ns,
              const std::string& name) {
    auto r = http_.del(ns_path(gvr, ns) + "/" + name);
    if (r.status >= 300 && r.status != 404)
      throw psthttp::HttpError("delete " + name + ": " +
                               std::to_string(r.status));
  }

  // Ensure the object exists with the desired spec: create if missing,
  // replace spec/labels via merge patch otherwise.
  void apply(const GVR& gvr, const std::string& ns, const Json& desired) {
    const std::string name =
        desired.get("metadata").get("name").as_string();
    auto existing = get(gvr, ns, name);
    if (!existing) {
      create(gvr, ns, desired);
      return;
    }
    merge_patch(gvr, ns, name, desired);
  }

  int watch(const GVR& gvr, const std::string& ns,
            const std::function<bool(const Json&)>& on_event,
            int max_seconds = 30) {
    std::string path = ns_path(gvr, ns) + "?watch=true";
    return http_.watch(
        path,
        [&](const std::string& line) {
          try {
            return on_event(Json::parse(line));
          } catch (const std::exception&) {
            return true;  // skip malformed frames
          }
        },
        max_seconds);
  }

 private:
  psthttp::Client http_;

  static std::string url_encode(const std::string& s) {
    std::string out;
    for (char c : s) {
      if (isalnum(static_cast<unsigned char>(c)) || c == '-' || c == '_' ||
          c == '.' || c == '~' || c == '=' || c == ',')
        out += c;
      else {
        char buf[8];
        snprintf(buf, sizeof(buf), "%%%02X", static_cast<unsigned char>(c));
        out += buf;
      }
    }
    return out;
  }
};

}  // namespace pstkube
