// Minimal JSON value + parser + serializer (self-contained; the build
// image has no nlohmann/jsoncpp). Covers the subset the operator needs:
// objects, arrays, strings (with escapes), numbers, bool, null.
//
// Role-equivalent of the JSON layer the reference operator gets from Go's
// encoding/json (reference: operator/api/v1alpha1/*_types.go marshalling).
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace pstjson {

class Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Object, Array };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(int64_t v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonObject o)
      : type_(Type::Object), obj_(std::make_shared<JsonObject>(std::move(o))) {}
  Json(JsonArray a)
      : type_(Type::Array), arr_(std::make_shared<JsonArray>(std::move(a))) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    return type_ == Type::Number ? static_cast<int64_t>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }

  // object access; get() is safe on non-objects (returns null)
  const Json& get(const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_->find(key);
    return it == obj_->end() ? null_json : it->second;
  }
  Json& operator[](const std::string& key) {
    if (type_ != Type::Object) {
      type_ = Type::Object;
      obj_ = std::make_shared<JsonObject>();
    }
    return (*obj_)[key];
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_->count(key) > 0;
  }
  const JsonObject& items() const {
    static const JsonObject empty;
    return type_ == Type::Object ? *obj_ : empty;
  }

  // array access
  const JsonArray& elements() const {
    static const JsonArray empty;
    return type_ == Type::Array ? *arr_ : empty;
  }
  void push_back(Json v) {
    if (type_ != Type::Array) {
      type_ = Type::Array;
      arr_ = std::make_shared<JsonArray>();
    }
    arr_->push_back(std::move(v));
  }
  size_t size() const {
    if (type_ == Type::Array) return arr_->size();
    if (type_ == Type::Object) return obj_->size();
    return 0;
  }

  // nested lookup: j.at_path({"spec", "replicas"})
  const Json& at_path(std::initializer_list<std::string> keys) const {
    const Json* cur = this;
    for (const auto& k : keys) cur = &cur->get(k);
    return *cur;
  }

  std::string dump(int indent = -1) const {
    std::ostringstream os;
    dump_to(os, indent, 0);
    return os.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size())
      throw std::runtime_error("json: trailing characters at " +
                               std::to_string(pos));
    return v;
  }

 private:
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::shared_ptr<JsonObject> obj_;
  std::shared_ptr<JsonArray> arr_;

  static void skip_ws(const std::string& s, size_t& p) {
    while (p < s.size() &&
           (s[p] == ' ' || s[p] == '\t' || s[p] == '\n' || s[p] == '\r'))
      p++;
  }

  static Json parse_value(const std::string& s, size_t& p) {
    skip_ws(s, p);
    if (p >= s.size()) throw std::runtime_error("json: unexpected end");
    char c = s[p];
    if (c == '{') return parse_object(s, p);
    if (c == '[') return parse_array(s, p);
    if (c == '"') return Json(parse_string(s, p));
    if (c == 't' || c == 'f') return parse_bool(s, p);
    if (c == 'n') {
      expect(s, p, "null");
      return Json();
    }
    return parse_number(s, p);
  }

  static void expect(const std::string& s, size_t& p, const char* lit) {
    size_t n = std::string(lit).size();
    if (s.compare(p, n, lit) != 0)
      throw std::runtime_error("json: expected " + std::string(lit));
    p += n;
  }

  static Json parse_bool(const std::string& s, size_t& p) {
    if (s[p] == 't') {
      expect(s, p, "true");
      return Json(true);
    }
    expect(s, p, "false");
    return Json(false);
  }

  static Json parse_number(const std::string& s, size_t& p) {
    size_t start = p;
    if (p < s.size() && (s[p] == '-' || s[p] == '+')) p++;
    while (p < s.size() &&
           (isdigit(s[p]) || s[p] == '.' || s[p] == 'e' || s[p] == 'E' ||
            s[p] == '-' || s[p] == '+'))
      p++;
    if (p == start) throw std::runtime_error("json: bad number");
    return Json(std::stod(s.substr(start, p - start)));
  }

  static std::string parse_string(const std::string& s, size_t& p) {
    if (s[p] != '"') throw std::runtime_error("json: expected string");
    p++;
    std::string out;
    while (p < s.size() && s[p] != '"') {
      char c = s[p++];
      if (c == '\\') {
        if (p >= s.size()) throw std::runtime_error("json: bad escape");
        char e = s[p++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (p + 4 > s.size()) throw std::runtime_error("json: bad \\u");
            unsigned cp = std::stoul(s.substr(p, 4), nullptr, 16);
            p += 4;
            // utf-8 encode (BMP only; surrogate pairs folded to '?')
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp >= 0xD800 && cp <= 0xDFFF) {
              out += '?';
              if (cp <= 0xDBFF && p + 6 <= s.size() && s[p] == '\\' &&
                  s[p + 1] == 'u')
                p += 6;  // swallow the low surrogate
            } else {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            throw std::runtime_error("json: bad escape");
        }
      } else {
        out += c;
      }
    }
    if (p >= s.size()) throw std::runtime_error("json: unterminated string");
    p++;  // closing quote
    return out;
  }

  static Json parse_object(const std::string& s, size_t& p) {
    p++;  // {
    JsonObject obj;
    skip_ws(s, p);
    if (p < s.size() && s[p] == '}') {
      p++;
      return Json(std::move(obj));
    }
    while (true) {
      skip_ws(s, p);
      std::string key = parse_string(s, p);
      skip_ws(s, p);
      if (p >= s.size() || s[p] != ':')
        throw std::runtime_error("json: expected ':'");
      p++;
      obj[key] = parse_value(s, p);
      skip_ws(s, p);
      if (p < s.size() && s[p] == ',') {
        p++;
        continue;
      }
      if (p < s.size() && s[p] == '}') {
        p++;
        break;
      }
      throw std::runtime_error("json: expected ',' or '}'");
    }
    return Json(std::move(obj));
  }

  static Json parse_array(const std::string& s, size_t& p) {
    p++;  // [
    JsonArray arr;
    skip_ws(s, p);
    if (p < s.size() && s[p] == ']') {
      p++;
      return Json(std::move(arr));
    }
    while (true) {
      arr.push_back(parse_value(s, p));
      skip_ws(s, p);
      if (p < s.size() && s[p] == ',') {
        p++;
        continue;
      }
      if (p < s.size() && s[p] == ']') {
        p++;
        break;
      }
      throw std::runtime_error("json: expected ',' or ']'");
    }
    return Json(std::move(arr));
  }

  static void dump_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        default:
          if (static_cast<unsigned char>(c) < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  void dump_to(std::ostringstream& os, int indent, int depth) const {
    auto pad = [&](int d) {
      if (indent >= 0) {
        os << '\n';
        for (int i = 0; i < indent * d; i++) os << ' ';
      }
    };
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 9.0e15) {
          os << static_cast<int64_t>(num_);
        } else {
          os << num_;
        }
        break;
      }
      case Type::String: dump_string(os, str_); break;
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : *obj_) {
          if (!first) os << ',';
          first = false;
          pad(depth + 1);
          dump_string(os, k);
          os << (indent >= 0 ? ": " : ":");
          v.dump_to(os, indent, depth + 1);
        }
        if (!first) pad(depth);
        os << '}';
        break;
      }
      case Type::Array: {
        os << '[';
        bool first = true;
        for (const auto& v : *arr_) {
          if (!first) os << ',';
          first = false;
          pad(depth + 1);
          v.dump_to(os, indent, depth + 1);
        }
        if (!first) pad(depth);
        os << ']';
        break;
      }
    }
  }
};

}  // namespace pstjson
