// Minimal blocking HTTP/1.1 server (one thread per connection) for the
// endpoint-picker service. Self-contained like http.hpp.
#pragma once

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <functional>
#include <string>
#include <thread>
#include <vector>

#include "http.hpp"

namespace psthttp {

struct Request {
  std::string method;
  std::string path;
  std::string body;
};

class Server {
 public:
  using Handler = std::function<Response(const Request&)>;

  explicit Server(Handler handler) : handler_(std::move(handler)) {}

  // binds and listens; returns the bound port (0 input = ephemeral)
  int start(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    addr.sin_addr.s_addr =
        host == "0.0.0.0" ? INADDR_ANY : inet_addr(host.c_str());
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw HttpError("bind failed");
    if (::listen(fd_, 64) != 0) throw HttpError("listen failed");
    socklen_t len = sizeof(addr);
    ::getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    running_ = true;
    accept_thread_ = std::thread([this] { accept_loop(); });
    return ntohs(addr.sin_port);
  }

  void stop() {
    running_ = false;
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
  }

  ~Server() { stop(); }

 private:
  Handler handler_;
  int fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;

  void accept_loop() {
    while (running_) {
      int cfd = ::accept(fd_, nullptr, nullptr);
      if (cfd < 0) break;
      std::thread([this, cfd] { serve_conn(cfd); }).detach();
    }
  }

  void serve_conn(int cfd) {
    struct timeval tv {30, 0};
    ::setsockopt(cfd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    try {
      while (true) {
        std::string head;
        char c;
        while (head.find("\r\n\r\n") == std::string::npos) {
          ssize_t n = ::recv(cfd, &c, 1, 0);
          if (n <= 0) { ::close(cfd); return; }
          head += c;
          if (head.size() > 1 << 20) { ::close(cfd); return; }
        }
        Request req;
        size_t sp1 = head.find(' ');
        size_t sp2 = head.find(' ', sp1 + 1);
        req.method = head.substr(0, sp1);
        req.path = head.substr(sp1 + 1, sp2 - sp1 - 1);
        size_t cl_pos = head.find("ontent-Length:");
        if (cl_pos == std::string::npos)
          cl_pos = head.find("ontent-length:");
        if (cl_pos != std::string::npos) {
          size_t n = std::stoul(head.substr(cl_pos + 14));
          req.body.reserve(n);
          char buf[8192];
          while (req.body.size() < n) {
            ssize_t got = ::recv(cfd, buf,
                                 std::min(sizeof(buf),
                                          n - req.body.size()), 0);
            if (got <= 0) { ::close(cfd); return; }
            req.body.append(buf, got);
          }
        }
        Response resp = handler_(req);
        std::string out =
            "HTTP/1.1 " + std::to_string(resp.status) + " OK\r\n" +
            "Content-Type: application/json\r\n" +
            "Content-Length: " + std::to_string(resp.body.size()) +
            "\r\n\r\n" + resp.body;
        if (::send(cfd, out.data(), out.size(), 0) < 0) break;
      }
    } catch (...) {
    }
    ::close(cfd);
  }
};

}  // namespace psthttp
