// Controllers: build desired Deployments/Services from the stack's CRDs
// and reconcile engine LoRA adapters. Capability parity with the
// reference's Go controllers (reference:
// operator/internal/controller/vllmruntime_controller.go:57 Reconcile /
// :190 deploymentForVLLMRuntime, vllmrouter_controller.go:61,
// cacheserver_controller.go:54, loraadapter_controller.go:73 + placement
// getOptimalPlacement:394 + engine load/unload calls :582/:598) —
// re-designed for the TPU engine's CLI and pod shape.
#pragma once

#include <algorithm>
#include <functional>
#include <future>
#include <iostream>
#include <limits>
#include <map>
#include <string>
#include <utility>
#include <vector>

#include "kube.hpp"

namespace pstop {

using pstjson::Json;
using pstjson::JsonArray;
using pstjson::JsonObject;
using pstkube::KubeClient;

inline void log(const std::string& msg) {
  std::cout << "[operator] " << msg << std::endl;
}

// -- helpers ---------------------------------------------------------------
inline Json meta(const std::string& name, const std::string& ns,
                 const JsonObject& labels, const Json& owner) {
  Json m = Json::object();
  m["name"] = name;
  m["namespace"] = ns;
  m["labels"] = Json(labels);
  if (owner.is_object() && owner.has("metadata")) {
    Json ref = Json::object();
    ref["apiVersion"] = owner.get("apiVersion");
    ref["kind"] = owner.get("kind");
    ref["name"] = owner.get("metadata").get("name");
    ref["uid"] = owner.get("metadata").get("uid");
    ref["controller"] = true;
    Json refs = Json::array();
    refs.push_back(ref);
    m["ownerReferences"] = refs;
  }
  return m;
}

inline void arg(JsonArray& args, const std::string& flag) {
  args.push_back(Json(flag));
}
inline void arg(JsonArray& args, const std::string& flag,
                const std::string& value) {
  args.push_back(Json(flag));
  args.push_back(Json(value));
}
inline void arg_if(JsonArray& args, const Json& spec, const std::string& key,
                   const std::string& flag) {
  const Json& v = spec.get(key);
  if (v.is_null()) return;
  if (v.is_bool()) {
    if (v.as_bool()) args.push_back(Json(flag));
    return;
  }
  args.push_back(Json(flag));
  args.push_back(
      Json(v.is_string() ? v.as_string() : std::to_string(v.as_int())));
}

inline Json deployment_shell(const Json& cr, const std::string& name,
                             const std::string& ns, const JsonObject& labels,
                             int replicas, Json container) {
  Json selector = Json::object();
  selector["matchLabels"] = Json(labels);

  Json podspec = Json::object();
  Json containers = Json::array();
  containers.push_back(container);
  podspec["containers"] = containers;

  Json tmplmeta = Json::object();
  tmplmeta["labels"] = Json(labels);
  Json tmpl = Json::object();
  tmpl["metadata"] = tmplmeta;
  tmpl["spec"] = podspec;

  Json spec = Json::object();
  spec["replicas"] = replicas;
  spec["selector"] = selector;
  spec["template"] = tmpl;

  Json d = Json::object();
  d["apiVersion"] = "apps/v1";
  d["kind"] = "Deployment";
  d["metadata"] = meta(name, ns, labels, cr);
  d["spec"] = spec;
  return d;
}

inline Json service_for(const Json& cr, const std::string& name,
                        const std::string& ns, const JsonObject& selector,
                        int port, int target_port) {
  Json p = Json::object();
  p["port"] = port;
  p["targetPort"] = target_port;
  Json ports = Json::array();
  ports.push_back(p);
  Json spec = Json::object();
  spec["selector"] = Json(selector);
  spec["ports"] = ports;
  Json s = Json::object();
  s["apiVersion"] = "v1";
  s["kind"] = "Service";
  s["metadata"] = meta(name, ns, selector, cr);
  s["spec"] = spec;
  return s;
}

inline std::string image_of(const Json& spec, const std::string& dflt) {
  const Json& img = spec.get("image");
  if (img.is_null()) return dflt;
  std::string repo = img.get("repository").as_string();
  std::string tag = img.get("tag").as_string();
  if (repo.empty()) return dflt;
  return repo + ":" + (tag.empty() ? "latest" : tag);
}

// -- TPURuntime: CR -> engine Deployment + Service ------------------------
// (reference: deploymentForVLLMRuntime builds the full `vllm serve` arg
// list, vllmruntime_controller.go:190-525; ours builds the TPU engine CLI)
inline Json engine_container(const Json& cr) {
  const Json& spec = cr.get("spec");
  const Json& model = spec.get("model");
  const Json& eng = spec.get("engine");
  const Json& kv = spec.get("kv");
  int port = static_cast<int>(spec.get("port").as_int(8000));

  JsonArray args;
  arg(args, "--model", model.get("modelURL").as_string());
  arg(args, "--host", "0.0.0.0");
  arg(args, "--port", std::to_string(port));
  if (!model.get("servedModelName").as_string().empty())
    arg(args, "--served-model-name",
        model.get("servedModelName").as_string());
  arg_if(args, eng, "tensorParallelSize", "--tensor-parallel-size");
  arg_if(args, eng, "pipelineParallelSize", "--pipeline-parallel-size");
  arg_if(args, eng, "maxModelLen", "--max-model-len");
  arg_if(args, eng, "maxNumSeqs", "--max-num-seqs");
  arg_if(args, eng, "blockSize", "--block-size");
  arg_if(args, eng, "dtype", "--dtype");
  arg_if(args, eng, "kvCacheDtype", "--kv-cache-dtype");
  arg_if(args, eng, "attentionImpl", "--attention-impl");
  arg_if(args, eng, "numSchedulerSteps", "--num-scheduler-steps");
  arg_if(args, eng, "numSpeculativeTokens", "--num-speculative-tokens");
  arg_if(args, eng, "precompileServing", "--precompile-serving");
  arg_if(args, eng, "schedulingPolicy", "--scheduling-policy");
  arg_if(args, eng, "enableLora", "--enable-lora");
  if (!eng.get("hbmUtilization").is_null())
    arg(args, "--hbm-utilization",
        std::to_string(eng.get("hbmUtilization").as_number()));
  arg_if(args, kv, "cpuOffloadGB", "--cpu-offload-gb");
  arg_if(args, kv, "diskOffloadDir", "--disk-offload-dir");
  arg_if(args, kv, "remoteCacheUrl", "--remote-cache-url");
  arg_if(args, kv, "kvControllerUrl", "--kv-controller-url");
  const std::string role = kv.get("role").as_string();
  if (!role.empty()) {
    arg(args, "--kv-role", role);
    if (role == "kv_producer")
      arg(args, "--kv-transfer-listen",
          "0.0.0.0:" + std::to_string(kv.get("transferPort").as_int(8200)));
    if (role == "kv_consumer" && !kv.get("peer").as_string().empty())
      arg(args, "--kv-peer", kv.get("peer").as_string());
  }
  for (const auto& extra : eng.get("extraArgs").elements())
    args.push_back(extra);

  Json c = Json::object();
  c["name"] = "engine";
  c["image"] = image_of(spec, "ghcr.io/example/production-stack-tpu:latest");
  Json cmd = Json::array();
  cmd.push_back(Json("python"));
  cmd.push_back(Json("-m"));
  cmd.push_back(Json("production_stack_tpu.engine"));
  c["command"] = cmd;
  c["args"] = Json(args);
  Json cport = Json::object();
  cport["containerPort"] = port;
  Json ports = Json::array();
  ports.push_back(cport);
  c["ports"] = ports;

  const Json& res = spec.get("resources");
  Json requests = Json::object();
  requests["cpu"] = res.get("cpu").is_null() ? Json("8") : res.get("cpu");
  requests["memory"] =
      res.get("memory").is_null() ? Json("32Gi") : res.get("memory");
  int tpu = static_cast<int>(res.get("tpu").as_int(8));
  requests["google.com/tpu"] = std::to_string(tpu);
  Json limits = Json::object();
  limits["google.com/tpu"] = std::to_string(tpu);
  Json resources = Json::object();
  resources["requests"] = requests;
  resources["limits"] = limits;
  c["resources"] = resources;
  return c;
}

inline JsonObject engine_labels(const Json& cr) {
  return JsonObject{
      {"app", Json("pst-engine")},
      {"model", cr.get("metadata").get("name")},
  };
}

inline void reconcile_tpuruntime(KubeClient& kube, const std::string& ns,
                                 const Json& cr) {
  const std::string name = cr.get("metadata").get("name").as_string();
  JsonObject labels = engine_labels(cr);
  int replicas =
      static_cast<int>(cr.get("spec").get("replicas").as_int(1));

  Json dep = deployment_shell(cr, name + "-engine", ns, labels, replicas,
                              engine_container(cr));
  // TPU node selector (reference pins runtimeClassName nvidia + gpu
  // resources; TPU pods pin the GKE TPU node pool instead)
  const Json& tpu = cr.get("spec").get("tpu");
  Json node_sel = Json::object();
  node_sel["cloud.google.com/gke-tpu-accelerator"] =
      tpu.get("accelerator").as_string().empty()
          ? Json("tpu-v5-lite-podslice")
          : tpu.get("accelerator");
  node_sel["cloud.google.com/gke-tpu-topology"] =
      tpu.get("topology").as_string().empty() ? Json("2x4")
                                              : tpu.get("topology");
  dep["spec"]["template"]["spec"]["nodeSelector"] = node_sel;

  kube.apply(pstkube::kDeployments, ns, dep);
  int port = static_cast<int>(cr.get("spec").get("port").as_int(8000));
  kube.apply(pstkube::kServices, ns,
             service_for(cr, name + "-engine", ns, labels, 80, port));

  // status from the Deployment
  auto live = kube.get(pstkube::kDeployments, ns, name + "-engine");
  Json status = Json::object();
  status["readyReplicas"] =
      live ? live->get("status").get("readyReplicas") : Json(0);
  status["ready"] =
      live && live->get("status").get("readyReplicas").as_int() >= replicas;
  kube.patch_status(pstkube::kTPURuntimes, ns, name, status);
}

// -- TPURouter: CR -> router Deployment + Service -------------------------
// (reference: vllmrouter_controller.go:61)
inline void reconcile_tpurouter(KubeClient& kube, const std::string& ns,
                                const Json& cr) {
  const std::string name = cr.get("metadata").get("name").as_string();
  const Json& spec = cr.get("spec");
  int port = static_cast<int>(spec.get("port").as_int(8001));
  JsonObject labels{{"app", Json(name + "-router")}};

  JsonArray args;
  arg(args, "--host", "0.0.0.0");
  arg(args, "--port", std::to_string(port));
  arg(args, "--service-discovery",
      spec.get("serviceDiscovery").as_string().empty()
          ? "k8s"
          : spec.get("serviceDiscovery").as_string());
  if (spec.get("serviceDiscovery").as_string() != "static") {
    arg(args, "--k8s-namespace", ns);
    arg(args, "--k8s-label-selector",
        spec.get("engineLabelSelector").as_string().empty()
            ? "app=pst-engine"
            : spec.get("engineLabelSelector").as_string());
  }
  arg(args, "--routing-logic",
      spec.get("routingLogic").as_string().empty()
          ? "roundrobin"
          : spec.get("routingLogic").as_string());
  arg_if(args, spec, "sessionKey", "--session-key");
  if (!spec.get("kvControllerPort").is_null())
    arg(args, "--kv-controller-url",
        "0.0.0.0:" + std::to_string(spec.get("kvControllerPort").as_int()));
  for (const auto& extra : spec.get("extraArgs").elements())
    args.push_back(extra);

  Json c = Json::object();
  c["name"] = "router";
  c["image"] = image_of(spec, "ghcr.io/example/production-stack-tpu:latest");
  Json cmd = Json::array();
  cmd.push_back(Json("python"));
  cmd.push_back(Json("-m"));
  cmd.push_back(Json("production_stack_tpu.router"));
  c["command"] = cmd;
  c["args"] = Json(args);
  Json cport = Json::object();
  cport["containerPort"] = port;
  Json ports = Json::array();
  ports.push_back(cport);
  c["ports"] = ports;

  int replicas = static_cast<int>(spec.get("replicas").as_int(1));
  kube.apply(pstkube::kDeployments, ns,
             deployment_shell(cr, name + "-router", ns, labels, replicas, c));
  kube.apply(pstkube::kServices, ns,
             service_for(cr, name + "-router", ns, labels, 80, port));

  auto live = kube.get(pstkube::kDeployments, ns, name + "-router");
  Json status = Json::object();
  status["readyReplicas"] =
      live ? live->get("status").get("readyReplicas") : Json(0);
  kube.patch_status(pstkube::kTPURouters, ns, name, status);
}

// -- CacheServer: CR -> cache server Deployment + Service -----------------
// (reference: cacheserver_controller.go:54 / deploymentForCacheServer:135)
inline void reconcile_cacheserver(KubeClient& kube, const std::string& ns,
                                  const Json& cr) {
  const std::string name = cr.get("metadata").get("name").as_string();
  const Json& spec = cr.get("spec");
  int port = static_cast<int>(spec.get("port").as_int(8100));
  JsonObject labels{{"app", Json(name + "-cache-server")}};

  JsonArray args;
  arg(args, "--host", "0.0.0.0");
  arg(args, "--port", std::to_string(port));
  arg(args, "--capacity-gb",
      std::to_string(spec.get("capacityGB").as_int(16)));
  arg_if(args, spec, "diskDir", "--disk-dir");

  Json c = Json::object();
  c["name"] = "cache-server";
  c["image"] = image_of(spec, "ghcr.io/example/production-stack-tpu:latest");
  Json cmd = Json::array();
  cmd.push_back(Json("python"));
  cmd.push_back(Json("-m"));
  cmd.push_back(Json("production_stack_tpu.kv.cache_server"));
  c["command"] = cmd;
  c["args"] = Json(args);
  Json cport = Json::object();
  cport["containerPort"] = port;
  Json ports = Json::array();
  ports.push_back(cport);
  c["ports"] = ports;

  int replicas = static_cast<int>(spec.get("replicas").as_int(1));
  kube.apply(
      pstkube::kDeployments, ns,
      deployment_shell(cr, name + "-cache-server", ns, labels, replicas, c));
  kube.apply(pstkube::kServices, ns,
             service_for(cr, name + "-cache-server", ns, labels, port, port));
}

// -- LoraAdapter: place + hot-load adapters onto engine pods --------------
// (reference: loraadapter_controller.go:73 Reconcile,
// getOptimalPlacement:394, load/unload engine calls :582/:598)
struct LoraPlacement {
  std::string pod_name;
  std::string pod_ip;
};

// how many LoRA adapters an engine currently serves: /v1/models lists one
// card per adapter with root == adapter path (!= id); the base model card
// has root == id. The adapter being reconciled is excluded (by its own
// path/name) — otherwise a steady-state resync would see its previous
// placement as "load" and hop the adapter to a fresh engine every tick.
// Engines that fail the probe (e.g. Running pods still loading weights)
// count INT_MAX so they sort LAST — preferring them would guarantee
// failed loads and placement flapping until the pod serves HTTP.
inline constexpr int kUnprobeableEngine = std::numeric_limits<int>::max();

inline int count_loaded_adapters(const std::string& ip, int port,
                                 const std::string& exclude_path = "",
                                 const std::string& exclude_name = "") {
  try {
    psthttp::Client engine(ip, port, 5);
    auto r = engine.get("/v1/models");
    if (r.status >= 300) return kUnprobeableEngine;
    Json data = Json::parse(r.body);
    int n = 0;
    for (const Json& card : data.get("data").elements()) {
      const std::string id = card.get("id").as_string();
      const std::string root = card.get("root").as_string();
      if (root.empty() || root == id) continue;  // base model card
      if (!exclude_path.empty() && root == exclude_path) continue;
      if (!exclude_name.empty() && id == exclude_name) continue;
      ++n;
    }
    return n;
  } catch (const std::exception&) {
    return kUnprobeableEngine;
  }
}

inline std::vector<LoraPlacement> pick_placements(
    const std::vector<Json>& pods, const std::string& algorithm,
    int max_engines,
    const std::function<int(const LoraPlacement&)>& adapter_count =
        nullptr) {
  std::vector<LoraPlacement> ready;
  for (const auto& pod : pods) {
    if (pod.get("status").get("phase").as_string() != "Running") continue;
    std::string ip = pod.get("status").get("podIP").as_string();
    if (ip.empty()) continue;
    ready.push_back(
        {pod.get("metadata").get("name").as_string(), ip});
  }
  std::sort(ready.begin(), ready.end(),
            [](const auto& a, const auto& b) {
              return a.pod_name < b.pod_name;
            });
  // "default": all ready engines; "ordered": first max_engines by name;
  // "equalized": spread adapters by current load — engines serving the
  // FEWEST adapters first (queried live via /v1/models), name-ordered
  // within a tie. Exceeds the reference bar (its getOptimalPlacement is
  // an acknowledged TODO returning the first N ready pods,
  // reference: loraadapter_controller.go:394-440).
  if (algorithm == "ordered" && max_engines > 0 &&
      static_cast<int>(ready.size()) > max_engines)
    ready.resize(max_engines);
  if (algorithm == "equalized" && !ready.empty()) {
    // one live query per engine, issued CONCURRENTLY (a sequential scan
    // would stall the reconcile loop up to 5s per unresponsive engine),
    // then a stable least-loaded sort
    std::vector<std::future<int>> counts;
    counts.reserve(ready.size());
    for (const auto& p : ready)
      counts.push_back(std::async(
          std::launch::async,
          [&adapter_count, p]() {
            return adapter_count ? adapter_count(p) : 0;
          }));
    std::vector<std::pair<int, LoraPlacement>> counted;
    counted.reserve(ready.size());
    for (size_t i = 0; i < ready.size(); ++i)
      counted.emplace_back(counts[i].get(), ready[i]);
    std::stable_sort(counted.begin(), counted.end(),
                     [](const auto& a, const auto& b) {
                       return a.first < b.first;
                     });
    ready.clear();
    for (const auto& cp : counted) ready.push_back(cp.second);
    if (max_engines > 0 &&
        static_cast<int>(ready.size()) > max_engines)
      ready.resize(max_engines);
  }
  return ready;
}

inline void reconcile_loraadapter(KubeClient& kube, const std::string& ns,
                                  const Json& cr, int engine_port) {
  const std::string name = cr.get("metadata").get("name").as_string();
  const Json& spec = cr.get("spec");
  const std::string adapter_name =
      spec.get("adapterName").as_string().empty()
          ? name
          : spec.get("adapterName").as_string();
  const std::string adapter_path = spec.get("adapterPath").as_string();
  const std::string base_model = spec.get("baseModel").as_string();
  const Json& placement = spec.get("placement");
  const std::string algorithm =
      placement.get("algorithm").as_string().empty()
          ? "default"
          : placement.get("algorithm").as_string();
  int max_engines = static_cast<int>(placement.get("maxEngines").as_int(0));

  std::string selector = "app=pst-engine";
  if (!base_model.empty()) selector += ",model=" + base_model;
  auto pods = kube.list(pstkube::kPods, ns, selector);
  auto placements = pick_placements(
      pods, algorithm, max_engines,
      [engine_port, &adapter_path, &adapter_name](const LoraPlacement& p) {
        return count_loaded_adapters(p.pod_ip, engine_port, adapter_path,
                                     adapter_name);
      });

  Json loaded = Json::array();
  for (const auto& p : placements) {
    try {
      psthttp::Client engine(p.pod_ip, engine_port, 10);
      Json body = Json::object();
      body["lora_name"] = adapter_name;
      body["lora_path"] = adapter_path;
      auto r = engine.post("/v1/load_lora_adapter", body.dump());
      Json entry = Json::object();
      entry["pod"] = p.pod_name;
      entry["status"] = (r.status < 300) ? "loaded" : "failed";
      loaded.push_back(entry);
      log("lora " + adapter_name + " -> " + p.pod_name + " (" +
          std::to_string(r.status) + ")");
    } catch (const std::exception& e) {
      Json entry = Json::object();
      entry["pod"] = p.pod_name;
      entry["status"] = std::string("error: ") + e.what();
      loaded.push_back(entry);
    }
  }
  Json status = Json::object();
  status["loadedAdapters"] = loaded;
  status["observedGeneration"] = cr.get("metadata").get("generation");
  kube.patch_status(pstkube::kLoraAdapters, ns, name, status);
}

}  // namespace pstop
