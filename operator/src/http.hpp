// Tiny HTTP/1.1 client over POSIX sockets (self-contained; no libcurl in
// the build image). Speaks plain HTTP: the operator reaches the
// kube-apiserver through a `kubectl proxy` sidecar on localhost, the
// standard no-TLS-client pattern (the reference operator instead links
// client-go with in-cluster TLS; see operator/README.md for the trade).
//
// Supports: request bodies, Content-Length and chunked responses, and
// line-streaming for watch endpoints (one JSON event per line).
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <functional>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>

namespace psthttp {

struct Response {
  int status = 0;
  std::map<std::string, std::string> headers;
  std::string body;
};

class HttpError : public std::runtime_error {
 public:
  explicit HttpError(const std::string& what) : std::runtime_error(what) {}
};

class Client {
 public:
  Client(std::string host, int port, int timeout_s = 30)
      : host_(std::move(host)), port_(port), timeout_s_(timeout_s) {}

  Response request(const std::string& method, const std::string& path,
                   const std::string& body = "",
                   const std::string& content_type = "application/json") {
    int fd = connect_fd();
    try {
      send_request(fd, method, path, body, content_type, /*close=*/true);
      Response r = read_response(fd);
      ::close(fd);
      return r;
    } catch (...) {
      ::close(fd);
      throw;
    }
  }

  Response get(const std::string& path) { return request("GET", path); }
  Response post(const std::string& path, const std::string& body,
                const std::string& ct = "application/json") {
    return request("POST", path, body, ct);
  }
  Response put(const std::string& path, const std::string& body) {
    return request("PUT", path, body);
  }
  Response patch(const std::string& path, const std::string& body,
                 const std::string& ct = "application/merge-patch+json") {
    return request("PATCH", path, body, ct);
  }
  Response del(const std::string& path) { return request("DELETE", path); }

  // Stream a watch endpoint: invokes on_line per newline-delimited JSON
  // event until the server closes, on_line returns false, or
  // max_seconds elapses. Returns the HTTP status.
  int watch(const std::string& path,
            const std::function<bool(const std::string&)>& on_line,
            int max_seconds = 30) {
    int fd = connect_fd(max_seconds);
    try {
      send_request(fd, "GET", path, "", "application/json", true);
      std::string headers = read_until(fd, "\r\n\r\n");
      int status = parse_status(headers);
      std::string buf;
      char chunk[4096];
      bool chunked = headers.find("chunked") != std::string::npos;
      while (true) {
        ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
        if (n <= 0) break;
        buf.append(chunk, n);
        // strip chunked-transfer framing lines (hex sizes) lazily: watch
        // events are newline-delimited JSON; framing lines never start
        // with '{' so they are filtered below
        size_t pos;
        while ((pos = buf.find('\n')) != std::string::npos) {
          std::string line = buf.substr(0, pos);
          buf.erase(0, pos + 1);
          while (!line.empty() &&
                 (line.back() == '\r' || line.back() == '\n'))
            line.pop_back();
          if (line.empty()) continue;
          if (chunked && line.find('{') == std::string::npos) continue;
          if (!on_line(line)) {
            ::close(fd);
            return status;
          }
        }
      }
      ::close(fd);
      return status;
    } catch (...) {
      ::close(fd);
      throw;
    }
  }

 private:
  std::string host_;
  int port_;
  int timeout_s_;

  int connect_fd(int timeout_override_s = 0) {
    struct addrinfo hints {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_str = std::to_string(port_);
    int rc = ::getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
    if (rc != 0)
      throw HttpError("resolve " + host_ + ": " + gai_strerror(rc));
    int fd = -1;
    for (auto* ai = res; ai; ai = ai->ai_next) {
      fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
      ::close(fd);
      fd = -1;
    }
    ::freeaddrinfo(res);
    if (fd < 0) throw HttpError("connect " + host_ + ":" + port_str);
    struct timeval tv {};
    tv.tv_sec = timeout_override_s > 0 ? timeout_override_s : timeout_s_;
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    return fd;
  }

  void send_request(int fd, const std::string& method,
                    const std::string& path, const std::string& body,
                    const std::string& content_type, bool close_conn) {
    std::ostringstream os;
    os << method << ' ' << path << " HTTP/1.1\r\n"
       << "Host: " << host_ << ':' << port_ << "\r\n"
       << "Accept: application/json\r\n";
    if (!body.empty())
      os << "Content-Type: " << content_type << "\r\n"
         << "Content-Length: " << body.size() << "\r\n";
    if (close_conn) os << "Connection: close\r\n";
    os << "\r\n" << body;
    std::string data = os.str();
    size_t sent = 0;
    while (sent < data.size()) {
      ssize_t n = ::send(fd, data.data() + sent, data.size() - sent, 0);
      if (n <= 0) throw HttpError("send failed");
      sent += n;
    }
  }

  std::string read_until(int fd, const std::string& delim) {
    std::string buf;
    char c;
    while (buf.find(delim) == std::string::npos) {
      ssize_t n = ::recv(fd, &c, 1, 0);
      if (n <= 0) throw HttpError("connection closed in headers");
      buf += c;
      if (buf.size() > 1 << 20) throw HttpError("headers too large");
    }
    return buf;
  }

  static int parse_status(const std::string& head) {
    size_t sp = head.find(' ');
    if (sp == std::string::npos) throw HttpError("bad status line");
    return std::stoi(head.substr(sp + 1, 3));
  }

  Response read_response(int fd) {
    std::string head = read_until(fd, "\r\n\r\n");
    Response r;
    r.status = parse_status(head);
    // headers
    std::istringstream hs(head);
    std::string line;
    std::getline(hs, line);  // status line
    while (std::getline(hs, line)) {
      while (!line.empty() && (line.back() == '\r')) line.pop_back();
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string k = line.substr(0, colon);
      for (auto& ch : k) ch = tolower(ch);
      std::string v = line.substr(colon + 1);
      while (!v.empty() && v.front() == ' ') v.erase(0, 1);
      r.headers[k] = v;
    }
    auto read_n = [&](size_t n) {
      std::string out;
      out.reserve(n);
      char chunk[8192];
      while (out.size() < n) {
        ssize_t got = ::recv(
            fd, chunk,
            std::min(sizeof(chunk), n - out.size()), 0);
        if (got <= 0) throw HttpError("connection closed in body");
        out.append(chunk, got);
      }
      return out;
    };
    auto te = r.headers.find("transfer-encoding");
    if (te != r.headers.end() &&
        te->second.find("chunked") != std::string::npos) {
      while (true) {
        std::string size_line = read_until(fd, "\r\n");
        size_t sz = std::stoul(size_line, nullptr, 16);
        if (sz == 0) {
          read_until(fd, "\r\n");  // trailing CRLF (ignore trailers)
          break;
        }
        r.body += read_n(sz);
        read_n(2);  // CRLF after each chunk
      }
    } else if (r.headers.count("content-length")) {
      r.body = read_n(std::stoul(r.headers["content-length"]));
    } else {
      // read to EOF (Connection: close)
      char chunk[8192];
      ssize_t n;
      while ((n = ::recv(fd, chunk, sizeof(chunk), 0)) > 0)
        r.body.append(chunk, n);
    }
    return r;
  }
};

}  // namespace psthttp
