// Gateway endpoint picker: native service the K8s Gateway API inference
// extension (or any gateway with an HTTP callout filter) can ask "which
// engine pod should take this request?".
//
// Capability parity with the reference's Go picker plugins (reference:
// src/gateway_inference_extension/*.go — round-robin picker (58 LoC),
// prefix-aware picker (213 LoC), and the KV-aware picker that queries the
// LMCache controller over TCP, kv_aware_picker.go:47 Pick /
// :90 lookupInstance / :116 queryInstance). Ours speaks the
// production_stack_tpu KV controller's length-prefixed JSON frames
// (kv/wire.py) for the kvaware strategy.
//
// API:  POST /pick
//       {"strategy": "roundrobin|prefixaware|kvaware",
//        "prompt": "...", "endpoints": ["http://10.0.0.1:8000", ...]}
//   ->  {"endpoint": "...", "reason": "..."}
#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <csignal>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "http_server.hpp"
#include "json.hpp"

using pstjson::Json;

// -- KV controller protocol client (kv/wire.py framing) ---------------------
// frame = u32 meta_len | u32 payload_len | meta JSON | payload
class KvControllerClient {
 public:
  KvControllerClient(std::string host, int port)
      : host_(std::move(host)), port_(port) {}

  // tokens -> {instance_id: matched_prefix_tokens}
  std::map<std::string, int64_t> lookup(const std::vector<int>& tokens) {
    Json msg = Json::object();
    msg["type"] = "lookup";
    Json toks = Json::array();
    for (int t : tokens) toks.push_back(Json(t));
    msg["tokens"] = toks;
    Json reply = call(msg);
    std::map<std::string, int64_t> out;
    for (const auto& [inst, n] : reply.get("matches").items())
      out[inst] = n.as_int();
    return out;
  }

 private:
  std::string host_;
  int port_;

  Json call(const Json& msg) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) throw std::runtime_error("socket failed");
    struct timeval tv {5, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port_);
    addr.sin_addr.s_addr = inet_addr(host_.c_str());
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
        0) {
      ::close(fd);
      throw std::runtime_error("controller connect failed");
    }
    std::string meta = msg.dump();
    uint32_t lens[2] = {htonl(static_cast<uint32_t>(meta.size())), 0};
    std::string frame(reinterpret_cast<char*>(lens), 8);
    frame += meta;
    if (::send(fd, frame.data(), frame.size(), 0) < 0) {
      ::close(fd);
      throw std::runtime_error("controller send failed");
    }
    auto read_n = [&](size_t n) {
      std::string out;
      out.reserve(n);
      char buf[4096];
      while (out.size() < n) {
        ssize_t got =
            ::recv(fd, buf, std::min(sizeof(buf), n - out.size()), 0);
        if (got <= 0) throw std::runtime_error("controller recv failed");
        out.append(buf, got);
      }
      return out;
    };
    std::string hdr = read_n(8);
    uint32_t meta_len, payload_len;
    memcpy(&meta_len, hdr.data(), 4);
    memcpy(&payload_len, hdr.data() + 4, 4);
    meta_len = ntohl(meta_len);
    payload_len = ntohl(payload_len);
    std::string body = read_n(meta_len);
    if (payload_len) read_n(payload_len);
    ::close(fd);
    return Json::parse(body);
  }
};

// -- pickers ----------------------------------------------------------------
static std::atomic<uint64_t> g_rr_counter{0};
static std::mutex g_prefix_mu;
// endpoint -> last prompts routed there (bounded), for prefix affinity
static std::map<std::string, std::vector<std::string>> g_prefix_history;

static size_t common_prefix(const std::string& a, const std::string& b) {
  size_t n = std::min(a.size(), b.size());
  size_t i = 0;
  while (i < n && a[i] == b[i]) i++;
  return i;
}

static std::string pick_roundrobin(const std::vector<std::string>& eps) {
  return eps[g_rr_counter++ % eps.size()];
}

static std::string pick_prefixaware(const std::vector<std::string>& eps,
                                    const std::string& prompt) {
  std::lock_guard<std::mutex> lk(g_prefix_mu);
  std::string best;
  size_t best_len = 0;
  for (const auto& ep : eps) {
    for (const auto& prev : g_prefix_history[ep]) {
      size_t n = common_prefix(prev, prompt);
      if (n > best_len) {
        best_len = n;
        best = ep;
      }
    }
  }
  // require a meaningful shared prefix (128 chars = one reference trie
  // chunk, prefix/hashtrie.py:24); else fall back to round-robin
  std::string chosen =
      (best_len >= 128) ? best : pick_roundrobin(eps);
  auto& hist = g_prefix_history[chosen];
  hist.push_back(prompt.substr(0, 4096));
  if (hist.size() > 64) hist.erase(hist.begin());
  return chosen;
}

static std::string pick_kvaware(const std::vector<std::string>& eps,
                                const std::string& prompt,
                                const std::string& controller_host,
                                int controller_port, std::string* reason) {
  try {
    KvControllerClient ctl(controller_host, controller_port);
    // byte tokenizer with BOS=256 (engine tokenizer="byte" contract;
    // production deployments colocate a real tokenizer-serving picker)
    std::vector<int> tokens;
    tokens.push_back(256);
    for (unsigned char c : prompt) tokens.push_back(c);
    auto matches = ctl.lookup(tokens);
    std::string best;
    int64_t best_n = 0;
    for (const auto& [inst, n] : matches) {
      if (n <= best_n) continue;
      for (const auto& ep : eps) {
        if (ep.find(inst) != std::string::npos || ep == inst) {
          best = ep;
          best_n = n;
          break;
        }
      }
    }
    if (!best.empty()) {
      *reason = "kv match " + std::to_string(best_n) + " tokens";
      return best;
    }
    *reason = "no kv match";
  } catch (const std::exception& e) {
    *reason = std::string("controller unavailable: ") + e.what();
  }
  return pick_roundrobin(eps);
}

int main(int argc, char** argv) {
  std::string host = "0.0.0.0";
  int port = 9002;
  std::string controller_host = "127.0.0.1";
  int controller_port = 9000;
  std::string default_strategy = "kvaware";
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() { return std::string(argv[++i]); };
    if (a == "--host") host = next();
    else if (a == "--port") port = std::stoi(next());
    else if (a == "--kv-controller-host") controller_host = next();
    else if (a == "--kv-controller-port")
      controller_port = std::stoi(next());
    else if (a == "--strategy") default_strategy = next();
  }

  psthttp::Server server([&](const psthttp::Request& req) {
    psthttp::Response resp;
    if (req.path == "/healthz") {
      resp.status = 200;
      resp.body = "{\"ok\": true}";
      return resp;
    }
    if (req.method != "POST" || req.path != "/pick") {
      resp.status = 404;
      resp.body = "{\"error\": \"POST /pick\"}";
      return resp;
    }
    try {
      Json body = Json::parse(req.body);
      std::vector<std::string> eps;
      for (const auto& e : body.get("endpoints").elements())
        eps.push_back(e.as_string());
      if (eps.empty()) {
        resp.status = 503;
        resp.body = "{\"error\": \"no endpoints\"}";
        return resp;
      }
      std::string strategy = body.get("strategy").as_string();
      if (strategy.empty()) strategy = default_strategy;
      std::string prompt = body.get("prompt").as_string();
      std::string reason = strategy;
      std::string chosen;
      if (strategy == "prefixaware")
        chosen = pick_prefixaware(eps, prompt);
      else if (strategy == "kvaware")
        chosen = pick_kvaware(eps, prompt, controller_host,
                              controller_port, &reason);
      else
        chosen = pick_roundrobin(eps);
      Json out = Json::object();
      out["endpoint"] = chosen;
      out["reason"] = reason;
      resp.status = 200;
      resp.body = out.dump();
    } catch (const std::exception& e) {
      resp.status = 400;
      resp.body = std::string("{\"error\": \"") + e.what() + "\"}";
    }
    return resp;
  });

  int bound = server.start(host, port);
  printf("[picker] listening on %s:%d (controller %s:%d)\n", host.c_str(),
         bound, controller_host.c_str(), controller_port);
  fflush(stdout);
  // block forever
  while (true) pause();
  return 0;
}
