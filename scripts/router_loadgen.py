#!/usr/bin/env python3
"""Router-only load harness: production traffic without a chip.

Drives thousands of concurrent STREAMING sessions through the real
router app (real TCP sockets, real aiohttp proxy hot path) against
in-process stub engines (tests/fake_engine.py), once per routing
algorithm, and writes ``ROUTER_BENCH.json``:

- per-phase p50/p99 from the router's own tiled phase decomposition
  (receive / route_decision / upstream_connect / upstream_ttft /
  stream_relay / finalize — stats/health.py sample ring),
- the phase-closure check (sum of phases vs independently measured
  e2e; the tiling contract makes this ≈ exact, and the smoke gate in
  tests/test_router_loadbench.py pins it within 5%),
- client-observed e2e/TTFT percentiles, RPS, error/retry counts, and
  the per-engine health scoreboard snapshot.

Everything runs in ONE asyncio process on a CPU box — engines, router,
and load clients — which is exactly what makes it a tier-1/CI
regression gate (no jax, no chip, no cluster). Usage:

    python scripts/router_loadgen.py --smoke          # CI profile
    python scripts/router_loadgen.py                  # full profile
    python scripts/router_loadgen.py --algorithms roundrobin,ttft \
        --requests 5000 --concurrency 1024

Exit status: 0 when every algorithm's gates pass (phase closure <= 5%,
error rate <= 1%), 2 otherwise — so a bare CI step fails loudly even
without the pytest gate.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import aiohttp  # noqa: E402
from aiohttp import web  # noqa: E402

from production_stack_tpu.router import parsers  # noqa: E402
from production_stack_tpu.router.routing_logic import (  # noqa: E402
    _reset_routing_logic,
)
from production_stack_tpu.router.service_discovery import (  # noqa: E402
    _reset_service_discovery,
)
from production_stack_tpu.router.stats.health import (  # noqa: E402
    PROXY_PHASES,
    _reset_engine_health_board,
    get_engine_health_board,
)
from tests.fake_engine import FakeEngine  # noqa: E402

DEFAULT_ALGORITHMS = (
    "roundrobin", "session", "prefixaware", "ttft", "latency",
)


def quiet_logs() -> None:
    """Silence per-request INFO logging: the proxy logs one line per
    routed request, which at harness volume measures the logger, not
    the data plane. Module loggers are non-propagating with their own
    levels (utils/log.py), so sweep existing ones AND set the env
    default for modules imported later (build_app imports lazily)."""
    import logging
    import os

    os.environ.setdefault("PST_LOG_LEVEL", "WARNING")
    for name in list(logging.root.manager.loggerDict):
        if name.startswith("production_stack_tpu"):
            logging.getLogger(name).setLevel(logging.WARNING)

# gates (also pinned by tests/test_router_loadbench.py)
CLOSURE_GATE = 0.05     # per-request |sum(phases) - e2e| / e2e
ERROR_RATE_GATE = 0.01


@dataclass
class RunConfig:
    requests: int = 2560          # per algorithm (5 algos -> 12k+ total)
    concurrency: int = 1024       # concurrent streaming sessions
    engines: int = 4
    tokens: int = 8               # streamed chunks per request
    tokens_per_sec: float = 2000.0
    engine_ttft_s: float = 0.0
    # dead-backend scenario: this many ADDITIONAL backends are listed in
    # static discovery but not listening (connection refused) — the
    # health-aware algorithms (ttft/latency) should stop routing to them
    # after the failure streak, while streak-blind ones keep burning a
    # connect-retry per pick. Requests still succeed either way (the
    # proxy retries on the remaining candidates), so the A/B shows up in
    # per-engine requests_total/retries, not the error gate.
    dead_engines: int = 0
    # two-role PD scenario (--pd): half the stub engines are labeled
    # prefill, half decode, and the run drives the `pd` routing policy —
    # each session's cold turn splits two-phase (1-token prefill on a
    # prefill-role engine, the stream on a decode-role engine) and
    # later turns route prefix-affine single-phase to the decode engine
    # holding the session. Attribution + gates land under result["pd"].
    pd: bool = False
    algorithms: tuple[str, ...] = DEFAULT_ALGORITHMS
    out: str = "ROUTER_BENCH.json"


def smoke_config() -> RunConfig:
    """The CI profile: >= 1k requests and >= 512 concurrent sessions
    per algorithm, small enough for an ungpu'd runner."""
    return RunConfig(requests=1024, concurrency=512, engines=4,
                     tokens=8, tokens_per_sec=2000.0)


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return -1.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _dist_ms(vals: list[float]) -> dict:
    s = sorted(vals)
    return {
        "count": len(s),
        "p50_ms": round(_percentile(s, 0.50) * 1e3, 4),
        "p99_ms": round(_percentile(s, 0.99) * 1e3, 4),
        "max_ms": round(s[-1] * 1e3, 4) if s else -1.0,
    }


async def _worker(
    wid: int,
    client: aiohttp.ClientSession,
    base: str,
    cfg: RunConfig,
    counter: dict,
    out: dict,
) -> None:
    """One streaming session: issues requests until the shared budget
    is spent. Session-affine headers + a per-session prompt prefix give
    the session/prefixaware algorithms something real to chew on."""
    if cfg.pd:
        # the PD policy's prefix affinity is trie-chunk (128 chars)
        # granular: pad the session preamble past one whole chunk so
        # turn 2+ routes single-phase to the session's decode engine
        prefix = f"session-{wid} " + "history " * 20
    else:
        prefix = f"session-{wid} shared history preamble. "
    while True:
        i = counter["next"]
        if i >= cfg.requests:
            return
        counter["next"] = i + 1
        body = {
            "model": "fake-model",
            "prompt": f"{prefix}turn {i} payload " + "x" * 64,
            "max_tokens": cfg.tokens,
            "stream": True,
        }
        ttft = None
        status = None
        # a 512-session burst against one listener can overflow the
        # kernel accept queue on a fast box — a CONNECT-stage reset is
        # the client's socket churn, not a router failure, so retry it
        # a couple of times before charging an error (anything after
        # bytes flowed still counts: the router owned the stream).
        # t0 resets per attempt for the same reason: the failed
        # connect + backoff are the client's time, and folding them
        # into ttft/e2e would skew the very tails the gates measure.
        for attempt in range(3):
            t0 = time.monotonic()
            try:
                async with client.post(
                    f"{base}/v1/completions", json=body,
                    headers={"x-user-id": f"user-{wid}"},
                ) as r:
                    status = r.status
                    async for _chunk in r.content.iter_any():
                        if ttft is None:
                            ttft = time.monotonic() - t0
                break
            except aiohttp.ClientConnectionError:
                if ttft is not None or attempt == 2:
                    status = None
                    break
                await asyncio.sleep(0.005 * (attempt + 1))
            except (aiohttp.ClientError, asyncio.TimeoutError):
                status = None
                break
        if status != 200:
            out["client_errors"] += 1
            continue
        out["e2e"].append(time.monotonic() - t0)
        if ttft is not None:
            out["ttft"].append(ttft)


async def run_algorithm(algo: str, cfg: RunConfig) -> dict:
    """One full load run: fresh singletons, fresh engines, fresh router
    on an ephemeral port, cfg.concurrency workers, cfg.requests total."""
    quiet_logs()
    from production_stack_tpu.router.app import build_app

    _reset_routing_logic()
    _reset_service_discovery()
    _reset_engine_health_board()

    labels: list[str | None] = [None] * cfg.engines
    if cfg.pd:
        if cfg.dead_engines:
            raise ValueError(
                "--pd and --dead-engines are separate scenarios"
            )
        n_prefill = max(1, cfg.engines // 2)
        labels = (
            ["prefill"] * n_prefill
            + ["decode"] * (cfg.engines - n_prefill)
        )
    engines = [
        FakeEngine(
            model="fake-model",
            tokens_per_sec=cfg.tokens_per_sec,
            ttft_s=cfg.engine_ttft_s,
            num_tokens=cfg.tokens,
            model_label=labels[i],
        )
        for i in range(cfg.engines)
    ]
    for e in engines:
        await e.start()
    # dead-backend scenario: bind a port but NEVER listen(2) and keep
    # the socket open for the whole run — every connect is refused
    # fast (the dead-pod signature the scoreboard keys on) and the
    # port can never be recycled to a live socket mid-run (a freed
    # ephemeral port could be re-assigned and turn the "dead" url
    # intermittently alive)
    import socket as _socket

    dead_urls: list[str] = []
    dead_socks: list[_socket.socket] = []
    for _ in range(cfg.dead_engines):
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        dead_socks.append(s)
        dead_urls.append(f"http://127.0.0.1:{s.getsockname()[1]}")

    backends = [e.url for e in engines] + dead_urls
    argv = [
        "--service-discovery", "static",
        "--static-backends", ",".join(backends),
        "--static-models", ",".join("fake-model" for _ in backends),
        "--routing-logic", algo,
        "--engine-stats-interval", "0.5",
        # empty url disables the kv-controller handshake for ttft
        # (no jax, no controller process on the load box)
        "--kv-controller-url", "",
    ]
    if algo == "session":
        argv += ["--session-key", "x-user-id"]
    if cfg.pd:
        # role labels ride static discovery (the stub engines don't run
        # the real /v1/models card-role handshake)
        argv += ["--static-model-labels",
                 ",".join(lbl or "" for lbl in labels)]
    args = parsers.parse_args(argv)
    router_app = build_app(args)

    # the sample ring must hold the whole run for exact percentiles
    get_engine_health_board().set_sample_capacity(cfg.requests * 2)

    runner = web.AppRunner(router_app.app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"

    out = {"e2e": [], "ttft": [], "client_errors": 0}
    counter = {"next": 0}
    t_start = time.monotonic()
    async with aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit=0),
        timeout=aiohttp.ClientTimeout(total=120),
    ) as client:
        await asyncio.gather(*(
            _worker(w, client, base, cfg, counter, out)
            for w in range(cfg.concurrency)
        ))
        wall_s = time.monotonic() - t_start
        # smoke-sanity: the data-plane histograms must be live
        async with client.get(f"{base}/metrics") as r:
            metrics_ok = "tpu_router:" in await r.text()
        async with client.get(f"{base}/debug/engines") as r:
            scoreboard = (await r.json())["engines"]

    board = get_engine_health_board()
    samples = list(board.samples)
    await runner.cleanup()
    for e in engines:
        await e.stop()
    for s in dead_socks:
        s.close()
    _reset_routing_logic()
    _reset_service_discovery()

    phase_vals: dict[str, list[float]] = {p: [] for p in PROXY_PHASES}
    closure_errs: list[float] = []
    router_errors = 0
    retries = sum(row.get("retries_total", 0) for row in scoreboard)
    for s in samples:
        if not s["ok"] and s["url"] not in dead_urls:
            # failed attempts against DEAD backends are the scenario's
            # own signal (reported under dead_backends below, compared
            # per algorithm); the error gate guards LIVE backends
            router_errors += 1
        for name, v in s["phases"].items():
            phase_vals.setdefault(name, []).append(v)
        if s["e2e_s"] > 0:
            gap = abs(sum(s["phases"].values()) - s["e2e_s"])
            closure_errs.append(gap / s["e2e_s"])

    completed = len(out["e2e"])
    result = {
        "requests": completed,
        "errors": out["client_errors"],
        "router_errors": router_errors,
        "retries": retries,
        "wall_s": round(wall_s, 3),
        "rps": round(completed / wall_s, 2) if wall_s > 0 else -1.0,
        "client": {
            "e2e": _dist_ms(out["e2e"]),
            "ttft": _dist_ms(out["ttft"]),
        },
        "phases": {
            name: _dist_ms(vals)
            for name, vals in phase_vals.items() if vals
        },
        "phase_closure": {
            "checked": len(closure_errs),
            "mean_rel_err": (
                round(sum(closure_errs) / len(closure_errs), 6)
                if closure_errs else -1.0
            ),
            "max_rel_err": (
                round(max(closure_errs), 6) if closure_errs else -1.0
            ),
        },
        "metrics_exported": metrics_ok,
        "per_engine": scoreboard,
    }
    if cfg.pd:
        # PD attribution from the stub engines' own request logs: the
        # two-phase split must put EXACTLY the 1-token non-streaming
        # prefill phases on prefill-role engines and every stream on a
        # decode-role engine; later session turns skip phase 1
        # entirely (prefix-affine single-phase resumes)
        pf_engines = [e for e in engines if e.model_label == "prefill"]
        dc_engines = [e for e in engines if e.model_label == "decode"]
        phase1 = [b for e in pf_engines for b in e.requests_seen]
        dc_reqs = [b for e in dc_engines for b in e.requests_seen]
        result["pd"] = {
            "prefill_backends": [e.url for e in pf_engines],
            "decode_backends": [e.url for e in dc_engines],
            "prefill_requests": len(phase1),
            "decode_requests": len(dc_reqs),
            "phase1_single_token": all(
                b.get("max_tokens") == 1 and not b.get("stream")
                for b in phase1
            ),
            "misrouted_streams": sum(
                1 for b in phase1 if b.get("stream")
            ),
            # requests that skipped the split (prefix-affine resumes)
            "resume_single_phase": max(0, len(dc_reqs) - len(phase1)),
        }
    if dead_urls:
        # dead-backend attribution: how much traffic each view of the
        # scenario burned on the dead urls (health-aware algorithms
        # should show a small, streak-bounded count; streak-blind ones
        # pay ~requests/engines in connect-retries)
        dead_rows = [r for r in scoreboard if r["url"] in dead_urls]
        result["dead_backends"] = {
            "urls": dead_urls,
            "requests_total": sum(
                r.get("requests_total", 0) for r in dead_rows
            ),
            "retries_total": sum(
                r.get("retries_total", 0) for r in dead_rows
            ),
        }
    return result


def gates_pass(algo_result: dict) -> list[str]:
    """Returns the list of violated gates (empty = pass)."""
    bad = []
    closure = algo_result["phase_closure"]
    if closure["checked"] == 0 or closure["max_rel_err"] > CLOSURE_GATE:
        bad.append(
            f"phase closure {closure['max_rel_err']} > {CLOSURE_GATE}"
        )
    total = max(1, algo_result["requests"] + algo_result["errors"])
    # the client-side and router-side counts see the SAME failures from
    # two vantage points — summing them would double-count each failed
    # request and trip the gate at half the intended threshold; gate on
    # whichever side saw more
    err_rate = max(
        algo_result["errors"], algo_result["router_errors"]
    ) / total
    if err_rate > ERROR_RATE_GATE:
        bad.append(f"error rate {err_rate:.4f} > {ERROR_RATE_GATE}")
    if not algo_result["metrics_exported"]:
        bad.append("tpu_router:* metrics missing from /metrics")
    pd = algo_result.get("pd")
    if pd:
        if pd["prefill_requests"] < 1:
            bad.append("pd: no prefill phases reached a prefill engine")
        if not pd["phase1_single_token"]:
            bad.append("pd: prefill-role engines saw non-phase-1 bodies")
        if pd["misrouted_streams"]:
            bad.append(
                f"pd: {pd['misrouted_streams']} streams hit a "
                "prefill-role engine"
            )
        if pd["decode_requests"] < algo_result["requests"]:
            bad.append(
                "pd: decode-role engines served fewer streams than "
                "completed requests"
            )
        if pd["resume_single_phase"] < 1:
            bad.append(
                "pd: no prefix-affine single-phase resume observed "
                "(PPD affinity broken)"
            )
    return bad


async def run_suite(cfg: RunConfig) -> dict:
    results: dict = {
        "config": {
            "requests_per_algorithm": cfg.requests,
            "concurrency": cfg.concurrency,
            "engines": cfg.engines,
            "tokens": cfg.tokens,
            "tokens_per_sec": cfg.tokens_per_sec,
        },
        "algorithms": {},
    }
    for algo in cfg.algorithms:
        print(f"[loadgen] {algo}: {cfg.requests} requests @ "
              f"{cfg.concurrency} concurrent ...", flush=True)
        r = await run_algorithm(algo, cfg)
        results["algorithms"][algo] = r
        print(
            f"[loadgen] {algo}: rps={r['rps']} "
            f"e2e_p99={r['client']['e2e']['p99_ms']}ms "
            f"errors={r['errors']}+{r['router_errors']} "
            f"closure_max={r['phase_closure']['max_rel_err']}",
            flush=True,
        )
    return results


def write_bench(results: dict, path: str | Path) -> None:
    Path(path).write_text(json.dumps(results, indent=2) + "\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="router_loadgen",
        description="router-only load harness (no chip, no jax)",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="CI profile: 1024 requests x 512 sessions "
                         "per algorithm")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per algorithm")
    ap.add_argument("--concurrency", type=int, default=None)
    ap.add_argument("--engines", type=int, default=None)
    ap.add_argument("--dead-engines", type=int, default=None,
                    help="additional listed-but-not-listening backends "
                         "(dead-pod scenario: health-aware algorithms "
                         "should stop routing to them)")
    ap.add_argument("--pd", action="store_true",
                    help="two-role PD scenario: half the stub engines "
                         "labeled prefill, half decode, driven through "
                         "the `pd` policy (cold turns split two-phase, "
                         "session resumes route prefix-affine)")
    ap.add_argument("--tokens", type=int, default=None)
    ap.add_argument("--tokens-per-sec", type=float, default=None)
    ap.add_argument("--engine-ttft-s", type=float, default=None)
    ap.add_argument("--algorithms", type=str, default=None,
                    help="comma list from: " + ",".join(
                        DEFAULT_ALGORITHMS))
    ap.add_argument("--out", type=str, default=None)
    ns = ap.parse_args(argv)

    cfg = smoke_config() if ns.smoke else RunConfig()
    for name in ("requests", "concurrency", "engines", "dead_engines",
                 "tokens", "tokens_per_sec", "engine_ttft_s", "out"):
        val = getattr(ns, name)
        if val is not None:
            setattr(cfg, name, val)
    if ns.algorithms:
        cfg.algorithms = tuple(
            a.strip() for a in ns.algorithms.split(",") if a.strip()
        )
    if ns.pd:
        cfg.pd = True
        if not ns.algorithms:
            cfg.algorithms = ("pd",)
        if ns.out is None:
            cfg.out = "ROUTER_BENCH_pd.json"

    quiet_logs()
    results = asyncio.run(run_suite(cfg))
    write_bench(results, cfg.out)
    print(f"[loadgen] wrote {cfg.out}")

    failed = False
    for algo, r in results["algorithms"].items():
        bad = gates_pass(r)
        if bad:
            failed = True
            print(f"[loadgen] GATE FAIL {algo}: {'; '.join(bad)}")
    return 2 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
