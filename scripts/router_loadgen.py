#!/usr/bin/env python3
"""Router-only load harness: production traffic without a chip.

Drives thousands of concurrent STREAMING sessions through the real
router app (real TCP sockets, real aiohttp proxy hot path) against
in-process stub engines (tests/fake_engine.py), once per routing
algorithm, and writes ``ROUTER_BENCH.json``:

- per-phase p50/p99 from the router's own tiled phase decomposition
  (receive / route_decision / upstream_connect / upstream_ttft /
  stream_relay / finalize — stats/health.py sample ring),
- the phase-closure check (sum of phases vs independently measured
  e2e; the tiling contract makes this ≈ exact, and the smoke gate in
  tests/test_router_loadbench.py pins it within 5%),
- client-observed e2e/TTFT percentiles, RPS, error/retry counts, and
  the per-engine health scoreboard snapshot.

Everything runs in ONE asyncio process on a CPU box — engines, router,
and load clients — which is exactly what makes it a tier-1/CI
regression gate (no jax, no chip, no cluster). Usage:

    python scripts/router_loadgen.py --smoke          # CI profile
    python scripts/router_loadgen.py                  # full profile
    python scripts/router_loadgen.py --algorithms roundrobin,ttft \
        --requests 5000 --concurrency 1024

Exit status: 0 when every algorithm's gates pass (phase closure <= 5%,
error rate <= 1%), 2 otherwise — so a bare CI step fails loudly even
without the pytest gate.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import math
import multiprocessing
import os
import sys
import tempfile
import time
from dataclasses import dataclass
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import aiohttp  # noqa: E402
from aiohttp import web  # noqa: E402

from production_stack_tpu.router import parsers  # noqa: E402
from production_stack_tpu.router.routing_logic import (  # noqa: E402
    _reset_routing_logic,
)
from production_stack_tpu.router.service_discovery import (  # noqa: E402
    _reset_service_discovery,
)
from production_stack_tpu.router.stats.health import (  # noqa: E402
    PROXY_PHASES,
    _reset_engine_health_board,
    get_engine_health_board,
)
from tests.fake_engine import FakeEngine  # noqa: E402

DEFAULT_ALGORITHMS = (
    "roundrobin", "session", "prefixaware", "ttft", "latency",
)


def quiet_logs() -> None:
    """Silence per-request INFO logging: the proxy logs one line per
    routed request, which at harness volume measures the logger, not
    the data plane. Module loggers are non-propagating with their own
    levels (utils/log.py), so sweep existing ones AND set the env
    default for modules imported later (build_app imports lazily)."""
    import logging
    import os

    os.environ.setdefault("PST_LOG_LEVEL", "WARNING")
    for name in list(logging.root.manager.loggerDict):
        if name.startswith("production_stack_tpu"):
            logging.getLogger(name).setLevel(logging.WARNING)

# gates (also pinned by tests/test_router_loadbench.py)
CLOSURE_GATE = 0.05     # per-request |sum(phases) - e2e| / e2e
ERROR_RATE_GATE = 0.01

# overload-scenario gates (also pinned by tests/test_router_overload.py):
# under a noisy-tenant burst the COMPLIANT tenants' p99 TTFT must stay
# within factor x baseline + slack (the slack absorbs shared-CI-runner
# scheduling noise at millisecond scales)
ISOLATION_P99_FACTOR = 3.0
ISOLATION_P99_SLACK_MS = 150.0
# SLO attribution gates: compliant tenants must end the run fully
# within their (generous) objectives while the noisy tenant's
# availability burn rate is visibly moving — per-tenant SLO
# attribution catching exactly what a fleet-average view hides
SLO_COMPLIANCE_GATE = 0.99


@dataclass
class RunConfig:
    requests: int = 2560          # per algorithm (5 algos -> 12k+ total)
    concurrency: int = 1024       # concurrent streaming sessions
    engines: int = 4
    tokens: int = 8               # streamed chunks per request
    tokens_per_sec: float = 2000.0
    engine_ttft_s: float = 0.0
    # dead-backend scenario: this many ADDITIONAL backends are listed in
    # static discovery but not listening (connection refused) — the
    # health-aware algorithms (ttft/latency) should stop routing to them
    # after the failure streak, while streak-blind ones keep burning a
    # connect-retry per pick. Requests still succeed either way (the
    # proxy retries on the remaining candidates), so the A/B shows up in
    # per-engine requests_total/retries, not the error gate.
    dead_engines: int = 0
    # two-role PD scenario (--pd): half the stub engines are labeled
    # prefill, half decode, and the run drives the `pd` routing policy —
    # each session's cold turn splits two-phase (1-token prefill on a
    # prefill-role engine, the stream on a decode-role engine) and
    # later turns route prefix-affine single-phase to the decode engine
    # holding the session. Attribution + gates land under result["pd"].
    pd: bool = False
    # multi-process client workers (--workers N): fork + one fresh
    # asyncio loop per worker, client results merged over a pipe — the
    # way past the ~150-180 RPS single-process client ceiling, so the
    # overload gates can run ABOVE the router's saturation point
    workers: int = 1
    # overload scenario (--overload): per-tenant admission budgets via
    # the dynamic config file, compliant tenants at a sustainable
    # open-loop rate, then ONE noisy tenant bursting at
    # ol_burst_factor x its budget — gates pin compliant-p99 isolation,
    # 429+Retry-After on every shed, zero upstream errors, and phase
    # closure across served AND shed requests
    overload: bool = False
    ol_noisy_rate: float = 40.0       # noisy tenant's budget, req/s
    ol_burst_factor: float = 3.0      # noisy offered rate / budget
    ol_compliant_tenants: int = 4
    ol_compliant_rps: float = 8.0     # per compliant tenant, open loop
    ol_phase_s: float = 10.0          # baseline / burst phase length
    algorithms: tuple[str, ...] = DEFAULT_ALGORITHMS
    out: str = "ROUTER_BENCH.json"


def smoke_config() -> RunConfig:
    """The CI profile: >= 1k requests and >= 512 concurrent sessions
    per algorithm, small enough for an ungpu'd runner."""
    return RunConfig(requests=1024, concurrency=512, engines=4,
                     tokens=8, tokens_per_sec=2000.0)


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return -1.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _dist_ms(vals: list[float]) -> dict:
    s = sorted(vals)
    return {
        "count": len(s),
        "p50_ms": round(_percentile(s, 0.50) * 1e3, 4),
        "p99_ms": round(_percentile(s, 0.99) * 1e3, 4),
        "max_ms": round(s[-1] * 1e3, 4) if s else -1.0,
    }


async def _worker(
    wid: int,
    client: aiohttp.ClientSession,
    base: str,
    cfg: RunConfig,
    counter: dict,
    out: dict,
) -> None:
    """One streaming session: issues requests until the shared budget
    is spent. Session-affine headers + a per-session prompt prefix give
    the session/prefixaware algorithms something real to chew on."""
    if cfg.pd:
        # the PD policy's prefix affinity is trie-chunk (128 chars)
        # granular: pad the session preamble past one whole chunk so
        # turn 2+ routes single-phase to the session's decode engine
        prefix = f"session-{wid} " + "history " * 20
    else:
        prefix = f"session-{wid} shared history preamble. "
    while True:
        i = counter["next"]
        if i >= cfg.requests:
            return
        counter["next"] = i + 1
        body = {
            "model": "fake-model",
            "prompt": f"{prefix}turn {i} payload " + "x" * 64,
            "max_tokens": cfg.tokens,
            "stream": True,
        }
        ttft = None
        status = None
        # a 512-session burst against one listener can overflow the
        # kernel accept queue on a fast box — a CONNECT-stage reset is
        # the client's socket churn, not a router failure, so retry it
        # a couple of times before charging an error (anything after
        # bytes flowed still counts: the router owned the stream).
        # t0 resets per attempt for the same reason: the failed
        # connect + backoff are the client's time, and folding them
        # into ttft/e2e would skew the very tails the gates measure.
        for attempt in range(3):
            t0 = time.monotonic()
            try:
                async with client.post(
                    f"{base}/v1/completions", json=body,
                    headers={"x-user-id": f"user-{wid}"},
                ) as r:
                    status = r.status
                    async for _chunk in r.content.iter_any():
                        if ttft is None:
                            ttft = time.monotonic() - t0
                break
            except aiohttp.ClientConnectionError:
                if ttft is not None or attempt == 2:
                    status = None
                    break
                await asyncio.sleep(0.005 * (attempt + 1))
            except (aiohttp.ClientError, asyncio.TimeoutError):
                status = None
                break
        if status != 200:
            out["client_errors"] += 1
            continue
        out["e2e"].append(time.monotonic() - t0)
        if ttft is not None:
            out["ttft"].append(ttft)


def _client_proc_main(
    conn, base: str, cfg: RunConfig, wid0: int, n_sessions: int,
    n_requests: int,
) -> None:
    """Entry point of ONE forked client worker process: a fresh asyncio
    loop driving ``n_sessions`` streaming sessions against the router's
    real TCP port (the parent keeps the router + engines), results sent
    back over the pipe. The fork happens after the router is listening;
    the child never touches the parent's loop or sockets."""
    quiet_logs()
    out = {"e2e": [], "ttft": [], "client_errors": 0}
    counter = {"next": 0}
    cfg_local = dataclasses.replace(cfg, requests=n_requests)

    async def go() -> None:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=120),
        ) as client:
            await asyncio.gather(*(
                _worker(wid0 + i, client, base, cfg_local, counter, out)
                for i in range(n_sessions)
            ))

    try:
        asyncio.run(go())
    finally:
        conn.send(out)
        conn.close()


async def _run_worker_processes(base: str, cfg: RunConfig) -> dict:
    """Fan the client load out over ``cfg.workers`` forked processes
    (one asyncio loop each) and merge their results. The parent's loop
    stays free to run the router; pipe reads/joins go through the
    default executor so they never block it."""
    ctx = multiprocessing.get_context("fork")
    procs = []
    req_share, req_rem = divmod(cfg.requests, cfg.workers)
    sess_share, sess_rem = divmod(cfg.concurrency, cfg.workers)
    wid0 = 0
    for w in range(cfg.workers):
        n_req = req_share + (1 if w < req_rem else 0)
        n_sess = max(1, sess_share + (1 if w < sess_rem else 0))
        parent_conn, child_conn = ctx.Pipe()
        proc = ctx.Process(
            target=_client_proc_main,
            args=(child_conn, base, cfg, wid0, n_sess, n_req),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        procs.append((proc, parent_conn))
        wid0 += n_sess
    loop = asyncio.get_running_loop()
    merged = {"e2e": [], "ttft": [], "client_errors": 0}
    outs = await asyncio.gather(*(
        loop.run_in_executor(None, conn.recv) for _, conn in procs
    ))
    for (proc, conn), out in zip(procs, outs):
        await loop.run_in_executor(None, proc.join)
        conn.close()
        merged["e2e"] += out["e2e"]
        merged["ttft"] += out["ttft"]
        merged["client_errors"] += out["client_errors"]
    return merged


async def run_algorithm(algo: str, cfg: RunConfig) -> dict:
    """One full load run: fresh singletons, fresh engines, fresh router
    on an ephemeral port, cfg.concurrency workers, cfg.requests total."""
    quiet_logs()
    from production_stack_tpu.router.app import build_app

    _reset_routing_logic()
    _reset_service_discovery()
    _reset_engine_health_board()

    labels: list[str | None] = [None] * cfg.engines
    if cfg.pd:
        if cfg.dead_engines:
            raise ValueError(
                "--pd and --dead-engines are separate scenarios"
            )
        n_prefill = max(1, cfg.engines // 2)
        labels = (
            ["prefill"] * n_prefill
            + ["decode"] * (cfg.engines - n_prefill)
        )
    engines = [
        FakeEngine(
            model="fake-model",
            tokens_per_sec=cfg.tokens_per_sec,
            ttft_s=cfg.engine_ttft_s,
            num_tokens=cfg.tokens,
            model_label=labels[i],
        )
        for i in range(cfg.engines)
    ]
    for e in engines:
        await e.start()
    # dead-backend scenario: bind a port but NEVER listen(2) and keep
    # the socket open for the whole run — every connect is refused
    # fast (the dead-pod signature the scoreboard keys on) and the
    # port can never be recycled to a live socket mid-run (a freed
    # ephemeral port could be re-assigned and turn the "dead" url
    # intermittently alive)
    import socket as _socket

    dead_urls: list[str] = []
    dead_socks: list[_socket.socket] = []
    for _ in range(cfg.dead_engines):
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        dead_socks.append(s)
        dead_urls.append(f"http://127.0.0.1:{s.getsockname()[1]}")

    backends = [e.url for e in engines] + dead_urls
    argv = [
        "--service-discovery", "static",
        "--static-backends", ",".join(backends),
        "--static-models", ",".join("fake-model" for _ in backends),
        "--routing-logic", algo,
        "--engine-stats-interval", "0.5",
        # empty url disables the kv-controller handshake for ttft
        # (no jax, no controller process on the load box)
        "--kv-controller-url", "",
    ]
    if algo == "session":
        argv += ["--session-key", "x-user-id"]
    if cfg.pd:
        # role labels ride static discovery (the stub engines don't run
        # the real /v1/models card-role handshake)
        argv += ["--static-model-labels",
                 ",".join(lbl or "" for lbl in labels)]
    args = parsers.parse_args(argv)
    router_app = build_app(args)

    # the sample ring must hold the whole run for exact percentiles
    get_engine_health_board().set_sample_capacity(cfg.requests * 2)

    runner = web.AppRunner(router_app.app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"

    out = {"e2e": [], "ttft": [], "client_errors": 0}
    counter = {"next": 0}
    t_start = time.monotonic()
    if cfg.workers > 1:
        out = await _run_worker_processes(base, cfg)
        wall_s = time.monotonic() - t_start
    else:
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0),
            timeout=aiohttp.ClientTimeout(total=120),
        ) as client:
            await asyncio.gather(*(
                _worker(w, client, base, cfg, counter, out)
                for w in range(cfg.concurrency)
            ))
            wall_s = time.monotonic() - t_start
    async with aiohttp.ClientSession() as probe:
        # smoke-sanity: the data-plane histograms must be live
        async with probe.get(f"{base}/metrics") as r:
            metrics_ok = "tpu_router:" in await r.text()
        async with probe.get(f"{base}/debug/engines") as r:
            scoreboard = (await r.json())["engines"]

    board = get_engine_health_board()
    samples = list(board.samples)
    await runner.cleanup()
    for e in engines:
        await e.stop()
    for s in dead_socks:
        s.close()
    _reset_routing_logic()
    _reset_service_discovery()

    phase_vals: dict[str, list[float]] = {p: [] for p in PROXY_PHASES}
    closure_errs: list[float] = []
    router_errors = 0
    retries = sum(row.get("retries_total", 0) for row in scoreboard)
    for s in samples:
        if not s["ok"] and s["url"] not in dead_urls:
            # failed attempts against DEAD backends are the scenario's
            # own signal (reported under dead_backends below, compared
            # per algorithm); the error gate guards LIVE backends
            router_errors += 1
        for name, v in s["phases"].items():
            phase_vals.setdefault(name, []).append(v)
        if s["e2e_s"] > 0:
            gap = abs(sum(s["phases"].values()) - s["e2e_s"])
            # floor the denominator at 1ms: closure guards LEAKED
            # latency; on a microsecond-scale request (admission
            # sheds) the handful of instructions between the final
            # mark and the independent e2e read is measurement
            # noise, not a leak
            closure_errs.append(gap / max(s["e2e_s"], 1e-3))

    completed = len(out["e2e"])
    result = {
        "requests": completed,
        "errors": out["client_errors"],
        "router_errors": router_errors,
        "retries": retries,
        "wall_s": round(wall_s, 3),
        "rps": round(completed / wall_s, 2) if wall_s > 0 else -1.0,
        "client": {
            "e2e": _dist_ms(out["e2e"]),
            "ttft": _dist_ms(out["ttft"]),
        },
        "phases": {
            name: _dist_ms(vals)
            for name, vals in phase_vals.items() if vals
        },
        "phase_closure": {
            "checked": len(closure_errs),
            "mean_rel_err": (
                round(sum(closure_errs) / len(closure_errs), 6)
                if closure_errs else -1.0
            ),
            "max_rel_err": (
                round(max(closure_errs), 6) if closure_errs else -1.0
            ),
        },
        "metrics_exported": metrics_ok,
        "per_engine": scoreboard,
    }
    if cfg.pd:
        # PD attribution from the stub engines' own request logs: the
        # two-phase split must put EXACTLY the 1-token non-streaming
        # prefill phases on prefill-role engines and every stream on a
        # decode-role engine; later session turns skip phase 1
        # entirely (prefix-affine single-phase resumes)
        pf_engines = [e for e in engines if e.model_label == "prefill"]
        dc_engines = [e for e in engines if e.model_label == "decode"]
        phase1 = [b for e in pf_engines for b in e.requests_seen]
        dc_reqs = [b for e in dc_engines for b in e.requests_seen]
        result["pd"] = {
            "prefill_backends": [e.url for e in pf_engines],
            "decode_backends": [e.url for e in dc_engines],
            "prefill_requests": len(phase1),
            "decode_requests": len(dc_reqs),
            "phase1_single_token": all(
                b.get("max_tokens") == 1 and not b.get("stream")
                for b in phase1
            ),
            "misrouted_streams": sum(
                1 for b in phase1 if b.get("stream")
            ),
            # requests that skipped the split (prefix-affine resumes)
            "resume_single_phase": max(0, len(dc_reqs) - len(phase1)),
        }
    if dead_urls:
        # dead-backend attribution: how much traffic each view of the
        # scenario burned on the dead urls (health-aware algorithms
        # should show a small, streak-bounded count; streak-blind ones
        # pay ~requests/engines in connect-retries)
        dead_rows = [r for r in scoreboard if r["url"] in dead_urls]
        result["dead_backends"] = {
            "urls": dead_urls,
            "requests_total": sum(
                r.get("requests_total", 0) for r in dead_rows
            ),
            "retries_total": sum(
                r.get("retries_total", 0) for r in dead_rows
            ),
        }
    return result


# -- overload scenario (admission control / noisy-tenant isolation) ---------
def _tenant_rec() -> dict:
    return {
        "e2e": [], "ttft": [], "served": 0, "errors": 0,
        "sheds": 0, "sheds_with_valid_retry_after": 0,
        "shed_reasons": {}, "retry_after_s": [],
    }


async def _one_shot(
    client: aiohttp.ClientSession, base: str, tenant: str, i: int,
    tokens: int, rec: dict,
) -> None:
    """One open-loop streaming request under a tenant identity. A 429
    is a SHED, validated on the spot: finite integer Retry-After
    header >= 1 AND a finite retry_after_s in the body."""
    body = {
        "model": "fake-model",
        "prompt": f"tenant {tenant} turn {i} payload " + "x" * 64,
        "max_tokens": tokens,
        "stream": True,
    }
    t0 = time.monotonic()
    ttft = None
    try:
        async with client.post(
            f"{base}/v1/completions", json=body,
            headers={"x-tenant-id": tenant},
        ) as r:
            if r.status == 429:
                rec["sheds"] += 1
                payload = await r.json()
                header = r.headers.get("Retry-After", "")
                retry_s = payload.get("error", {}).get("retry_after_s")
                reason = payload.get("error", {}).get("code", "?")
                rec["shed_reasons"][reason] = (
                    rec["shed_reasons"].get(reason, 0) + 1
                )
                if (
                    header.isdigit() and int(header) >= 1
                    and isinstance(retry_s, (int, float))
                    and math.isfinite(retry_s) and retry_s > 0
                ):
                    rec["sheds_with_valid_retry_after"] += 1
                    rec["retry_after_s"].append(float(retry_s))
                return
            async for _chunk in r.content.iter_any():
                if ttft is None:
                    ttft = time.monotonic() - t0
            if r.status == 200:
                rec["served"] += 1
                rec["e2e"].append(time.monotonic() - t0)
                if ttft is not None:
                    rec["ttft"].append(ttft)
            else:
                rec["errors"] += 1
    except (aiohttp.ClientError, asyncio.TimeoutError):
        rec["errors"] += 1


async def _tenant_gun(
    client: aiohttp.ClientSession, base: str, tenant: str, rps: float,
    duration_s: float, tokens: int, rec: dict,
) -> None:
    """Open-loop arrivals at a fixed rate: requests FIRE on the clock
    whether or not earlier ones finished — the arrival process a rate
    limiter actually faces (a closed loop would self-throttle and
    never expose the burst)."""
    interval = 1.0 / rps
    t_end = time.monotonic() + duration_s
    pending: list[asyncio.Task] = []
    i = 0
    while time.monotonic() < t_end:
        pending.append(asyncio.ensure_future(
            _one_shot(client, base, tenant, i, tokens, rec)
        ))
        i += 1
        await asyncio.sleep(interval)
    await asyncio.gather(*pending)


def _phase_summary(recs: dict[str, dict]) -> dict:
    """Merge per-tenant records into the compliant/noisy summary the
    gates read."""
    def merge(names):
        agg = _tenant_rec()
        for name in names:
            rec = recs[name]
            for key in ("e2e", "ttft", "retry_after_s"):
                agg[key] += rec[key]
            for key in ("served", "errors", "sheds",
                        "sheds_with_valid_retry_after"):
                agg[key] += rec[key]
            for reason, n in rec["shed_reasons"].items():
                agg["shed_reasons"][reason] = (
                    agg["shed_reasons"].get(reason, 0) + n
                )
        return {
            "served": agg["served"],
            "errors": agg["errors"],
            "sheds": agg["sheds"],
            "sheds_with_valid_retry_after":
                agg["sheds_with_valid_retry_after"],
            "shed_reasons": agg["shed_reasons"],
            "retry_after": _dist_ms(agg["retry_after_s"]),
            "e2e": _dist_ms(agg["e2e"]),
            "ttft": _dist_ms(agg["ttft"]),
        }

    compliant = [t for t in recs if t.startswith("compliant")]
    out = {"compliant": merge(compliant)}
    if "noisy" in recs:
        out["noisy"] = merge(["noisy"])
    return out


async def run_overload(cfg: RunConfig) -> dict:
    """The admission acceptance scenario: compliant tenants at a
    sustainable open-loop rate, measured ALONE (baseline) and then
    BESIDE a noisy tenant bursting at ``ol_burst_factor`` x its
    token-bucket budget. Budgets reach the router through the dynamic
    config file (the live-reload wiring is part of what this proves);
    the noisy tenant runs at `batch` priority, the compliant ones at
    `interactive`, so the ladder + buckets shed the right traffic."""
    quiet_logs()
    from production_stack_tpu.router.admission import (
        _reset_admission_controller,
        get_admission_controller,
    )
    from production_stack_tpu.router.app import build_app
    from production_stack_tpu.router.stats.slo import _reset_slo_tracker

    _reset_routing_logic()
    _reset_service_discovery()
    _reset_engine_health_board()
    _reset_admission_controller()
    _reset_slo_tracker()

    engines = [
        FakeEngine(
            model="fake-model",
            tokens_per_sec=cfg.tokens_per_sec,
            ttft_s=cfg.engine_ttft_s,
            num_tokens=cfg.tokens,
        )
        for _ in range(cfg.engines)
    ]
    for e in engines:
        await e.start()

    # per-tenant budgets via the dynamic config file — the exact
    # operator path (admission: section, applied by the watcher at
    # startup and on change)
    tenants: dict = {
        "noisy": {
            "rate": cfg.ol_noisy_rate,
            "burst": cfg.ol_noisy_rate,
            "priority": "batch",
        },
    }
    for i in range(cfg.ol_compliant_tenants):
        tenants[f"compliant-{i}"] = {
            # 3x headroom: a compliant tenant's own budget must never
            # be what sheds it in this scenario
            "rate": cfg.ol_compliant_rps * 3,
            "priority": "interactive",
        }
    # SLO objectives beside the budgets (slo: section, same watcher
    # path): compliant tenants get deliberately generous targets — a
    # well-behaved tenant must end the run fully compliant — while the
    # noisy tenant's availability objective makes its sheds VISIBLE as
    # error-budget burn (per-tenant attribution a fleet view hides)
    slo_objectives: dict = {
        "noisy": {"availability": 0.99},
    }
    for i in range(cfg.ol_compliant_tenants):
        slo_objectives[f"compliant-{i}"] = {
            "ttft_p99_s": 2.0,
            "error_rate": 0.01,
            "availability": 0.999,
        }
    dyn_cfg = tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    )
    json.dump({
        "admission": {"tenants": tenants},
        "slo": {"objectives": slo_objectives},
    }, dyn_cfg)
    dyn_cfg.close()

    argv = [
        "--service-discovery", "static",
        "--static-backends", ",".join(e.url for e in engines),
        "--static-models", ",".join("fake-model" for _ in engines),
        "--routing-logic", "roundrobin",
        "--engine-stats-interval", "0.5",
        "--kv-controller-url", "",
        "--dynamic-config-json", dyn_cfg.name,
    ]
    args = parsers.parse_args(argv)
    router_app = build_app(args)
    expected_total = int(
        (cfg.ol_compliant_tenants * cfg.ol_compliant_rps * 2
         + cfg.ol_noisy_rate * cfg.ol_burst_factor) * cfg.ol_phase_s
    )
    get_engine_health_board().set_sample_capacity(expected_total * 2)

    runner = web.AppRunner(router_app.app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"

    compliant_names = [
        f"compliant-{i}" for i in range(cfg.ol_compliant_tenants)
    ]
    async with aiohttp.ClientSession(
        connector=aiohttp.TCPConnector(limit=0),
        timeout=aiohttp.ClientTimeout(total=120),
    ) as client:
        # the dynamic-config watcher applied the budgets at startup;
        # fail fast here rather than measuring an unlimited router
        assert get_admission_controller().tenant_limits, (
            "admission budgets from the dynamic config were not applied"
        )
        from production_stack_tpu.router.stats.slo import (
            get_slo_tracker,
        )

        assert get_slo_tracker().active, (
            "slo objectives from the dynamic config were not applied"
        )
        # phase A — baseline: compliant tenants alone
        base_recs = {t: _tenant_rec() for t in compliant_names}
        await asyncio.gather(*(
            _tenant_gun(client, base, t, cfg.ol_compliant_rps,
                        cfg.ol_phase_s, cfg.tokens, base_recs[t])
            for t in compliant_names
        ))
        await asyncio.sleep(0.25)
        # phase B — burst: same compliant traffic + the noisy tenant
        # at burst_factor x its budget
        burst_recs = {t: _tenant_rec() for t in compliant_names}
        burst_recs["noisy"] = _tenant_rec()
        guns = [
            _tenant_gun(client, base, t, cfg.ol_compliant_rps,
                        cfg.ol_phase_s, cfg.tokens, burst_recs[t])
            for t in compliant_names
        ]
        guns.append(_tenant_gun(
            client, base, "noisy",
            cfg.ol_noisy_rate * cfg.ol_burst_factor,
            cfg.ol_phase_s, cfg.tokens, burst_recs["noisy"],
        ))
        await asyncio.gather(*guns)

        async with client.get(f"{base}/metrics") as r:
            metrics_text = await r.text()
        async with client.get(f"{base}/debug/admission") as r:
            admission_debug = await r.json()
        async with client.get(f"{base}/debug/slo") as r:
            slo_debug = await r.json()
        async with client.get(f"{base}/debug/engines") as r:
            scoreboard = (await r.json())["engines"]

    board = get_engine_health_board()
    samples = list(board.samples)
    await runner.cleanup()
    for e in engines:
        await e.stop()
    os.unlink(dyn_cfg.name)
    _reset_routing_logic()
    _reset_service_discovery()
    _reset_admission_controller()
    _reset_slo_tracker()

    # phase closure across SERVED and SHED requests alike: the shed
    # path's single tiled `shed` mark must keep sum(phases) == e2e
    closure_errs: list[float] = []
    shed_samples = served_samples = router_errors = 0
    for s in samples:
        if s.get("shed"):
            shed_samples += 1
        elif s["ok"]:
            served_samples += 1
        else:
            router_errors += 1
        if s["e2e_s"] > 0:
            gap = abs(sum(s["phases"].values()) - s["e2e_s"])
            # same 1ms denominator floor as run_algorithm: µs-scale
            # shed responses must not turn instruction-level jitter
            # into closure-gate failures
            closure_errs.append(gap / max(s["e2e_s"], 1e-3))

    upstream_errors_total = sum(
        row.get("errors_total", 0) for row in scoreboard
    )
    result = {
        "scenario": {
            "noisy_rate_rps": cfg.ol_noisy_rate,
            "burst_factor": cfg.ol_burst_factor,
            "compliant_tenants": cfg.ol_compliant_tenants,
            "compliant_rps_each": cfg.ol_compliant_rps,
            "phase_s": cfg.ol_phase_s,
            "engines": cfg.engines,
            "tokens": cfg.tokens,
        },
        "baseline": _phase_summary(base_recs),
        "burst": _phase_summary(burst_recs),
        "router_errors": router_errors,
        "upstream_errors_total": upstream_errors_total,
        "samples": {
            "served": served_samples,
            "shed": shed_samples,
        },
        "phase_closure": {
            "checked": len(closure_errs),
            "mean_rel_err": (
                round(sum(closure_errs) / len(closure_errs), 6)
                if closure_errs else -1.0
            ),
            "max_rel_err": (
                round(max(closure_errs), 6) if closure_errs else -1.0
            ),
        },
        "admission_metrics_exported": (
            "tpu_router:admission_sheds" in metrics_text
            and "tpu_router:shed_seconds" in metrics_text
        ),
        "slo": _slo_summary(slo_debug, metrics_text),
        "admission_debug": {
            "load": admission_debug.get("load"),
            "admitted_total": admission_debug.get("admitted_total"),
            "shed_total": admission_debug.get("shed_total"),
        },
        "per_engine": scoreboard,
    }
    return result


def _slo_summary(slo_debug: dict, metrics_text: str) -> dict:
    """Fold the /debug/slo payload into the per-tenant attribution
    summary the SLO gates read: each compliant tenant's WORST
    fast-window compliance + total violations, and the noisy tenant's
    availability burn rate (its sheds made visible as budget burn)."""
    compliant: dict[str, dict] = {}
    noisy_burn = -1.0
    noisy_violations = 0
    for row in slo_debug.get("tenants", []):
        tenant = row["tenant"]
        fast = row.get("fast", {})
        if tenant == "noisy":
            avail = fast.get("availability", {})
            noisy_burn = max(noisy_burn, avail.get("burn_rate", -1.0))
            noisy_violations += sum(row["violations_total"].values())
        elif tenant.startswith("compliant"):
            rec = compliant.setdefault(tenant, {
                "compliance_ratio": 1.0, "violations_total": 0,
                "requests": 0,
            })
            for view in fast.values():
                rec["compliance_ratio"] = min(
                    rec["compliance_ratio"],
                    1.0 - view["violation_fraction"],
                )
                rec["requests"] = max(rec["requests"], view["requests"])
            rec["violations_total"] += sum(
                row["violations_total"].values()
            )
    return {
        "active": slo_debug.get("active", False),
        "compliant": compliant,
        "noisy_availability_burn_rate": noisy_burn,
        "noisy_violations_total": noisy_violations,
        "metrics_exported": (
            "tpu_router:slo_compliance_ratio" in metrics_text
            and "tpu_router:slo_burn_rate" in metrics_text
        ),
        # the ISSUE 15 acceptance scrape: the autoscale family must be
        # present on a LIVE /metrics render
        "fleet_metrics_exported": all(
            name in metrics_text for name in (
                "tpu_router:fleet_load_score",
                "tpu_router:fleet_awake_engines",
                "tpu_router:fleet_desired_replicas_hint",
            )
        ),
    }


def overload_gates(r: dict) -> list[str]:
    """Violated acceptance gates for the overload scenario (empty =
    pass)."""
    bad = []
    base_p99 = r["baseline"]["compliant"]["ttft"]["p99_ms"]
    burst_p99 = r["burst"]["compliant"]["ttft"]["p99_ms"]
    bound = base_p99 * ISOLATION_P99_FACTOR + ISOLATION_P99_SLACK_MS
    if base_p99 < 0 or burst_p99 < 0:
        bad.append("isolation: missing compliant TTFT samples")
    elif burst_p99 > bound:
        bad.append(
            f"isolation: compliant p99 TTFT {burst_p99}ms under burst "
            f"> bound {round(bound, 3)}ms (baseline {base_p99}ms)"
        )
    noisy = r["burst"]["noisy"]
    if noisy["sheds"] < 1:
        bad.append("noisy tenant was never shed (bucket not enforced)")
    for phase in ("baseline", "burst"):
        for who, rec in r[phase].items():
            if rec["sheds"] != rec["sheds_with_valid_retry_after"]:
                bad.append(
                    f"{phase}/{who}: "
                    f"{rec['sheds'] - rec['sheds_with_valid_retry_after']}"
                    " sheds without a finite Retry-After"
                )
            if rec["errors"]:
                bad.append(f"{phase}/{who}: {rec['errors']} client errors")
    compliant_sheds = (
        r["baseline"]["compliant"]["sheds"]
        + r["burst"]["compliant"]["sheds"]
    )
    if compliant_sheds:
        bad.append(
            f"{compliant_sheds} compliant-tenant requests shed "
            "(noisy tenant's burst leaked into other budgets)"
        )
    if r["upstream_errors_total"] or r["router_errors"]:
        bad.append(
            f"upstream errors: {r['upstream_errors_total']} on engines, "
            f"{r['router_errors']} router-observed"
        )
    closure = r["phase_closure"]
    if closure["checked"] == 0 or closure["max_rel_err"] > CLOSURE_GATE:
        bad.append(
            f"phase closure {closure['max_rel_err']} > {CLOSURE_GATE}"
        )
    if r["samples"]["shed"] < 1:
        bad.append("no shed samples in the phase ring (closure gate "
                   "never covered the shed path)")
    if not r["admission_metrics_exported"]:
        bad.append("tpu_router:admission_* metrics missing from /metrics")
    # SLO attribution: compliant tenants hold their objectives while
    # the noisy tenant's budget burn is observed moving
    slo = r.get("slo", {})
    if not slo.get("active"):
        bad.append("slo objectives were not applied (tracker inactive)")
    else:
        if not slo["compliant"]:
            bad.append("no compliant-tenant SLO rows tracked")
        for tenant, rec in slo["compliant"].items():
            if rec["violations_total"]:
                bad.append(
                    f"slo: compliant {tenant} has "
                    f"{rec['violations_total']} violations"
                )
            if rec["compliance_ratio"] < SLO_COMPLIANCE_GATE:
                bad.append(
                    f"slo: compliant {tenant} compliance "
                    f"{rec['compliance_ratio']} < {SLO_COMPLIANCE_GATE}"
                )
        if slo["noisy_availability_burn_rate"] <= 0:
            bad.append(
                "slo: noisy tenant's availability burn rate never "
                "moved (sheds are not reaching the tracker)"
            )
        if not slo["metrics_exported"]:
            bad.append("tpu_router:slo_* metrics missing from /metrics")
        if not slo["fleet_metrics_exported"]:
            bad.append(
                "tpu_router:fleet_* metrics missing from /metrics"
            )
    # the noisy tenant must not be able to push more than its budget
    # through: burst capacity + rate x phase + scheduling slack
    scn = r["scenario"]
    budget = (
        scn["noisy_rate_rps"] * (scn["phase_s"] + 1.0)
        + scn["noisy_rate_rps"]  # initial burst capacity
    )
    if noisy["served"] > budget * 1.15:
        bad.append(
            f"noisy tenant served {noisy['served']} > budget "
            f"~{budget:.0f} (bucket leaking)"
        )
    return bad


def gates_pass(algo_result: dict) -> list[str]:
    """Returns the list of violated gates (empty = pass)."""
    bad = []
    closure = algo_result["phase_closure"]
    if closure["checked"] == 0 or closure["max_rel_err"] > CLOSURE_GATE:
        bad.append(
            f"phase closure {closure['max_rel_err']} > {CLOSURE_GATE}"
        )
    total = max(1, algo_result["requests"] + algo_result["errors"])
    # the client-side and router-side counts see the SAME failures from
    # two vantage points — summing them would double-count each failed
    # request and trip the gate at half the intended threshold; gate on
    # whichever side saw more
    err_rate = max(
        algo_result["errors"], algo_result["router_errors"]
    ) / total
    if err_rate > ERROR_RATE_GATE:
        bad.append(f"error rate {err_rate:.4f} > {ERROR_RATE_GATE}")
    if not algo_result["metrics_exported"]:
        bad.append("tpu_router:* metrics missing from /metrics")
    pd = algo_result.get("pd")
    if pd:
        if pd["prefill_requests"] < 1:
            bad.append("pd: no prefill phases reached a prefill engine")
        if not pd["phase1_single_token"]:
            bad.append("pd: prefill-role engines saw non-phase-1 bodies")
        if pd["misrouted_streams"]:
            bad.append(
                f"pd: {pd['misrouted_streams']} streams hit a "
                "prefill-role engine"
            )
        if pd["decode_requests"] < algo_result["requests"]:
            bad.append(
                "pd: decode-role engines served fewer streams than "
                "completed requests"
            )
        if pd["resume_single_phase"] < 1:
            bad.append(
                "pd: no prefix-affine single-phase resume observed "
                "(PPD affinity broken)"
            )
    return bad


async def run_suite(cfg: RunConfig) -> dict:
    results: dict = {
        "config": {
            "requests_per_algorithm": cfg.requests,
            "concurrency": cfg.concurrency,
            "engines": cfg.engines,
            "tokens": cfg.tokens,
            "tokens_per_sec": cfg.tokens_per_sec,
            "workers": cfg.workers,
        },
        "algorithms": {},
    }
    for algo in cfg.algorithms:
        print(f"[loadgen] {algo}: {cfg.requests} requests @ "
              f"{cfg.concurrency} concurrent ...", flush=True)
        r = await run_algorithm(algo, cfg)
        results["algorithms"][algo] = r
        print(
            f"[loadgen] {algo}: rps={r['rps']} "
            f"e2e_p99={r['client']['e2e']['p99_ms']}ms "
            f"errors={r['errors']}+{r['router_errors']} "
            f"closure_max={r['phase_closure']['max_rel_err']}",
            flush=True,
        )
    return results


def write_bench(results: dict, path: str | Path) -> None:
    Path(path).write_text(json.dumps(results, indent=2) + "\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="router_loadgen",
        description="router-only load harness (no chip, no jax)",
    )
    ap.add_argument("--smoke", action="store_true",
                    help="CI profile: 1024 requests x 512 sessions "
                         "per algorithm")
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per algorithm")
    ap.add_argument("--concurrency", type=int, default=None)
    ap.add_argument("--workers", type=int, default=None,
                    help="client worker PROCESSES (fork + one asyncio "
                         "loop each, results merged): pushes the load "
                         "past the single-process ~150-180 RPS client "
                         "ceiling so gates run above the router's "
                         "saturation point")
    ap.add_argument("--engines", type=int, default=None)
    ap.add_argument("--dead-engines", type=int, default=None,
                    help="additional listed-but-not-listening backends "
                         "(dead-pod scenario: health-aware algorithms "
                         "should stop routing to them)")
    ap.add_argument("--pd", action="store_true",
                    help="two-role PD scenario: half the stub engines "
                         "labeled prefill, half decode, driven through "
                         "the `pd` policy (cold turns split two-phase, "
                         "session resumes route prefix-affine)")
    ap.add_argument("--overload", action="store_true",
                    help="admission-control overload scenario: "
                         "compliant tenants at a sustainable open-loop "
                         "rate measured alone (baseline) then beside a "
                         "noisy tenant bursting at 3x its token-bucket "
                         "budget — gates pin compliant-p99 isolation, "
                         "429+finite-Retry-After on every shed, zero "
                         "upstream errors, and phase closure over "
                         "served AND shed requests")
    ap.add_argument("--noisy-rate", type=float, default=None,
                    help="overload: noisy tenant budget in req/s")
    ap.add_argument("--burst-factor", type=float, default=None,
                    help="overload: noisy offered rate / budget")
    ap.add_argument("--compliant-tenants", type=int, default=None,
                    help="overload: number of well-behaved tenants")
    ap.add_argument("--compliant-rps", type=float, default=None,
                    help="overload: open-loop req/s per compliant "
                         "tenant")
    ap.add_argument("--phase-s", type=float, default=None,
                    help="overload: baseline/burst phase length")
    ap.add_argument("--tokens", type=int, default=None)
    ap.add_argument("--tokens-per-sec", type=float, default=None)
    ap.add_argument("--engine-ttft-s", type=float, default=None)
    ap.add_argument("--algorithms", type=str, default=None,
                    help="comma list from: " + ",".join(
                        DEFAULT_ALGORITHMS))
    ap.add_argument("--out", type=str, default=None)
    ns = ap.parse_args(argv)

    cfg = smoke_config() if ns.smoke else RunConfig()
    for name, attr in (
        ("requests", "requests"), ("concurrency", "concurrency"),
        ("workers", "workers"), ("engines", "engines"),
        ("dead_engines", "dead_engines"), ("tokens", "tokens"),
        ("tokens_per_sec", "tokens_per_sec"),
        ("engine_ttft_s", "engine_ttft_s"), ("out", "out"),
        ("noisy_rate", "ol_noisy_rate"),
        ("burst_factor", "ol_burst_factor"),
        ("compliant_tenants", "ol_compliant_tenants"),
        ("compliant_rps", "ol_compliant_rps"),
        ("phase_s", "ol_phase_s"),
    ):
        val = getattr(ns, name)
        if val is not None:
            setattr(cfg, attr, val)
    if ns.algorithms:
        cfg.algorithms = tuple(
            a.strip() for a in ns.algorithms.split(",") if a.strip()
        )
    if ns.pd:
        cfg.pd = True
        if not ns.algorithms:
            cfg.algorithms = ("pd",)
        if ns.out is None:
            cfg.out = "ROUTER_BENCH_pd.json"

    quiet_logs()
    if ns.overload:
        if ns.smoke and ns.phase_s is None:
            cfg.ol_phase_s = 6.0  # CI profile: ~12s of load
        if ns.out is None:
            cfg.out = "ROUTER_BENCH_overload.json"
        result = asyncio.run(run_overload(cfg))
        results = {
            "config": dataclasses.asdict(cfg),
            "overload": result,
        }
        write_bench(results, cfg.out)
        print(f"[loadgen] wrote {cfg.out}")
        burst = result["burst"]
        print(
            f"[loadgen] overload: compliant_p99_ttft="
            f"{result['baseline']['compliant']['ttft']['p99_ms']}ms->"
            f"{burst['compliant']['ttft']['p99_ms']}ms "
            f"noisy_served={burst['noisy']['served']} "
            f"noisy_sheds={burst['noisy']['sheds']} "
            f"noisy_slo_burn="
            f"{result['slo']['noisy_availability_burn_rate']} "
            f"upstream_errors={result['upstream_errors_total']} "
            f"closure_max={result['phase_closure']['max_rel_err']}",
            flush=True,
        )
        bad = overload_gates(result)
        if bad:
            print(f"[loadgen] GATE FAIL overload: {'; '.join(bad)}")
            return 2
        return 0

    results = asyncio.run(run_suite(cfg))
    write_bench(results, cfg.out)
    print(f"[loadgen] wrote {cfg.out}")

    failed = False
    for algo, r in results["algorithms"].items():
        bad = gates_pass(r)
        if bad:
            failed = True
            print(f"[loadgen] GATE FAIL {algo}: {'; '.join(bad)}")
    return 2 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
