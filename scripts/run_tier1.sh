#!/usr/bin/env bash
# Tier-1 verify: the exact command ROADMAP.md pins (hermetic CPU run of
# the fast test suite), wrapped so every PR measures the same thing.
# Prints DOTS_PASSED=<n> — record it in ROADMAP.md as the baseline the
# next PR must not regress.
#
# Usage: scripts/run_tier1.sh [extra pytest args...]
set -o pipefail
cd "$(dirname "$0")/.."
LOG="${TIER1_LOG:-/tmp/_t1.log}"
TIMEOUT="${TIER1_TIMEOUT:-870}"
rm -f "$LOG"
timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
