"""Bounded-timeout TPU backend probe. Prints one JSON line; exit 0 iff up.

Chip-session hygiene (see README): short-lived, daemon-thread bounded,
never SIGKILLed. Used by scripts/tpu_probe_loop.sh to build the
timestamped availability record (TPU_ATTEMPTS.log) the round requires.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from production_stack_tpu.utils.chip_guard import (  # noqa: E402
    ChipBusyError,
    acquire_chip_lock,
)

try:
    _lock = acquire_chip_lock()
except ChipBusyError:
    print(json.dumps({
        "ok": False,
        "error": "skipped: chip lock held (another TPU process owns it)",
        "dt": 0.0,
    }))
    raise SystemExit(2)

box = {}


def probe():
    try:
        import jax

        box["devices"] = [str(d) for d in jax.devices()]
    except Exception as e:  # noqa: BLE001
        box["error"] = f"{type(e).__name__}: {e}"


t = threading.Thread(target=probe, daemon=True)
t0 = time.time()
t.start()
t.join(90)
dt = round(time.time() - t0, 1)
if "devices" in box:
    print(json.dumps({"ok": True, "devices": box["devices"], "dt": dt}))
    raise SystemExit(0)
err = box.get("error", "timeout after 90s")
print(json.dumps({"ok": False, "error": str(err)[:300], "dt": dt}))
raise SystemExit(1)
