#!/usr/bin/env python3
"""Shared KV cache server smoke harness — no jax, no chip, REAL sockets.

Starts a `kv.cache_server.KVCacheServer` (RAM + disk tier, short TTL)
in-process, then drives the full verb surface from blocking
`kv.remote.CacheClient`s the way a fleet of engines would: batched
multi-block PUT frames, single/chain/batch GETs, `lookup` prefix-depth
probes, LRU spill RAM -> disk, TTL expiry, health + metrics, and N
concurrent writer/reader clients hammering the server at once (the
IO-outside-lock discipline under load). Writes a stats artifact
(default CACHE_SERVER_BENCH.json) and exits non-zero on any gate
violation — the CI `kv-cache-server` job runs exactly this.

Usage: python scripts/cache_server_smoke.py [--out CACHE_SERVER_BENCH.json]
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time

import numpy as np

REPO = __file__.rsplit("/scripts/", 1)[0]
sys.path.insert(0, REPO)

from production_stack_tpu.kv.cache_server import (  # noqa: E402
    InProcessCacheServer,
    probe,
)
from production_stack_tpu.kv.remote import CacheClient  # noqa: E402

BLOCK_NBYTES = 64 * 1024          # ~a tiny-model KV block
N_CHAINS = 24                     # distinct hash chains (sessions)
CHAIN_LEN = 16                    # blocks per chain
BATCH = 8                         # blocks per put_batch frame
N_CLIENTS = 8                     # concurrent writer/reader threads
RAM_CAPACITY = 40 * BLOCK_NBYTES  # forces a RAM -> disk spill
TTL_S = 2.0


def blk(chain: int, i: int) -> np.ndarray:
    arr = np.full(
        (2, 2, BLOCK_NBYTES // 16), chain * 1000 + i, dtype=np.float32
    )
    return arr


def chain_hashes(chain: int) -> list[int]:
    return [chain * 100_000 + i for i in range(CHAIN_LEN)]


def drive_one_client(port: int, chains: list[int], errors: list[str]):
    cl = CacheClient("127.0.0.1", port)
    try:
        for c in chains:
            hashes = chain_hashes(c)
            # batched write-behind shape: CHAIN_LEN blocks in
            # CHAIN_LEN/BATCH frames
            for ofs in range(0, CHAIN_LEN, BATCH):
                cl.put_batch([
                    (hashes[i], blk(c, i))
                    for i in range(ofs, ofs + BATCH)
                ])
            depth = cl.lookup(hashes)
            if depth != CHAIN_LEN:
                errors.append(
                    f"chain {c}: lookup depth {depth} != {CHAIN_LEN}"
                )
            blocks = cl.get_chain(hashes)
            if len(blocks) != CHAIN_LEN:
                errors.append(
                    f"chain {c}: get_chain returned {len(blocks)}"
                )
                continue
            for i, got in enumerate(blocks):
                if got[0, 0, 0] != c * 1000 + i:
                    errors.append(f"chain {c} block {i}: wrong payload")
                    break
    except Exception as e:  # noqa: BLE001 — any client failure fails CI
        errors.append(f"client exception: {type(e).__name__}: {e}")
    finally:
        cl.close()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="CACHE_SERVER_BENCH.json")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="pst-cache-smoke-")
    box = InProcessCacheServer(
        capacity_bytes=RAM_CAPACITY, disk_dir=tmp, ttl_s=TTL_S
    )
    port = box.port

    t0 = time.monotonic()
    errors: list[str] = []
    threads = []
    per_client = max(1, N_CHAINS // N_CLIENTS)
    for w in range(N_CLIENTS):
        chains = list(range(w * per_client, (w + 1) * per_client))
        t = threading.Thread(
            target=drive_one_client, args=(port, chains, errors)
        )
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=120)
        if t.is_alive():
            errors.append("client thread hung (lock held across IO?)")
    drive_s = time.monotonic() - t0

    cl = CacheClient("127.0.0.1", port)
    stats_mid = cl.stats()
    # the RAM tier cannot hold the working set: the spill MUST have
    # cascaded into the disk tier
    disk_blocks = next(
        (t["blocks"] for t in stats_mid["tiers"] if t["tier"] == "disk"), 0
    )
    if disk_blocks <= 0:
        errors.append("RAM->disk spill never happened")
    if probe(f"127.0.0.1:{port}") != 0:
        errors.append("health probe failed on a live server")
    _, metrics_payload = cl.call({"type": "metrics"})
    if b"pst_cache_server_hit_rate" not in metrics_payload:
        errors.append("metrics verb missing hit_rate")

    # TTL: everything expires once idle past the deadline
    time.sleep(TTL_S + 0.5)
    depth_after_ttl = cl.lookup(chain_hashes(0))
    stats_end = cl.stats()
    if depth_after_ttl != 0:
        errors.append(
            f"TTL never expired chain 0 (depth {depth_after_ttl})"
        )
    if stats_end["expired"] <= 0:
        errors.append("expired counter never moved")
    cl.close()

    n_blocks = N_CLIENTS * per_client * CHAIN_LEN
    result = {
        "ok": not errors,
        "errors": errors,
        "clients": N_CLIENTS,
        "chains": N_CLIENTS * per_client,
        "blocks_put": n_blocks,
        "block_nbytes": BLOCK_NBYTES,
        "drive_seconds": round(drive_s, 3),
        "put_blocks_per_s": round(n_blocks / max(drive_s, 1e-9), 1),
        "stats_after_drive": stats_mid,
        "stats_after_ttl": stats_end,
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ("stats_after_drive", "stats_after_ttl")},
                     indent=2))

    box.stop()
    if errors:
        print("FAIL:\n  " + "\n  ".join(errors), file=sys.stderr)
        return 2
    print(f"OK: {n_blocks} blocks over {N_CLIENTS} clients in "
          f"{drive_s:.2f}s, disk spill {disk_blocks} blocks, TTL clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
