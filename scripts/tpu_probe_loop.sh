#!/bin/bash
# Probe the TPU every ~15 min, appending to TPU_ATTEMPTS.log.
# Exits 0 the moment the backend answers (so a watcher can run bench.py).
# Touch TPU_PROBE_PAUSE in the repo root to skip probes (e.g. while a
# bench run owns the chip) — hygiene: one TPU process at a time.
cd "$(dirname "$0")/.." || exit 1
while true; do
  if [ -f TPU_PROBE_PAUSE ]; then
    sleep 60
    continue
  fi
  echo "$(date -u +%FT%TZ) probe start" >> TPU_ATTEMPTS.log
  if python scripts/tpu_probe.py >> TPU_ATTEMPTS.log 2>/dev/null; then
    echo "$(date -u +%FT%TZ) TPU UP" >> TPU_ATTEMPTS.log
    exit 0
  fi
  # short interval: chip windows as short as ~20 min have been observed
  # (TPU_ATTEMPTS.log 2026-07-31), so detection delay must stay small
  sleep "${TPU_PROBE_INTERVAL:-240}"
done
