#!/usr/bin/env bash
# stackcheck: the repo-native AST analyzer for async/dispatch/lock hazards
# (production_stack_tpu/analysis/). Mirrors run_tier1.sh: every PR runs
# the same invocation CI and the tier-1 suite enforce — zero unsuppressed
# findings over the package, or non-zero exit.
#
# Usage: scripts/run_stackcheck.sh [extra stackcheck args...]
#   e.g. scripts/run_stackcheck.sh --show-suppressed
#        scripts/run_stackcheck.sh --json
#        scripts/run_stackcheck.sh --select silent-except,blocking-async
#
# Stdlib-only: needs no jax, aiohttp, or any install — safe as a
# pre-push hook on a bare CPython.
set -o pipefail
cd "$(dirname "$0")/.."
exec python -m production_stack_tpu.analysis production_stack_tpu/ "$@"
