#!/usr/bin/env bash
# Install the observability stack for production-stack-tpu (reference:
# observability/install.sh): kube-prometheus-stack + prometheus-adapter +
# the TPU stack Grafana dashboard as a sidecar-loaded configmap.
set -euo pipefail
cd "$(dirname "$0")"

NS="${MONITORING_NS:-monitoring}"

helm repo add prometheus-community \
  https://prometheus-community.github.io/helm-charts >/dev/null
helm repo update >/dev/null

echo "Installing kube-prometheus-stack into namespace ${NS}..."
helm upgrade --install kube-prom-stack \
  prometheus-community/kube-prometheus-stack \
  --namespace "${NS}" --create-namespace \
  -f kube-prom-stack.yaml

echo "Installing prometheus-adapter (custom metrics for HPA)..."
helm upgrade --install prometheus-adapter \
  prometheus-community/prometheus-adapter \
  --namespace "${NS}" \
  -f prom-adapter.yaml

echo "Loading the TPU stack dashboard..."
kubectl create configmap tpu-stack-dashboard \
  --from-file=tpu-stack-dashboard.json \
  --namespace "${NS}" \
  --dry-run=client -o yaml |
  kubectl label -f - grafana_dashboard=1 --local --dry-run=client -o yaml |
  kubectl apply -f -

echo "Done. Port-forward Grafana with:"
echo "  kubectl -n ${NS} port-forward svc/kube-prom-stack-grafana 3000:80"
