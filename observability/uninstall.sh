#!/usr/bin/env bash
# Tear down the observability stack (reference: observability/uninstall.sh).
set -euo pipefail
NS="${MONITORING_NS:-monitoring}"
kubectl delete configmap tpu-stack-dashboard -n "${NS}" --ignore-not-found
helm uninstall prometheus-adapter -n "${NS}" || true
helm uninstall kube-prom-stack -n "${NS}" || true
