#!/usr/bin/env bash
# Prepare a ShareGPT workload file for --sharegpt-path (role parity with
# reference prepare_sharegpt_data.sh, which downloads the HF dump).
#
# With network access, download the standard cleaned split:
#   curl -L -o sharegpt.json \
#     https://huggingface.co/datasets/anon8231489123/ShareGPT_Vicuna_unfiltered/resolve/main/ShareGPT_V3_unfiltered_cleaned_split.json
#
# Air-gapped environments (CI, this repo's tests) can generate a
# synthetic file with the same schema instead:
#   ./prepare_sharegpt_data.sh --synthetic sharegpt.json [num_convs]
set -euo pipefail

if [[ "${1:-}" == "--synthetic" ]]; then
  OUT="${2:-sharegpt.json}"
  N="${3:-64}"
  python3 - "$OUT" "$N" << 'EOF'
import json, random, string, sys

out, n = sys.argv[1], int(sys.argv[2])
rng = random.Random(0)

def text(words):
    return " ".join(
        "".join(rng.choices(string.ascii_lowercase, k=rng.randint(3, 9)))
        for _ in range(words)
    )

data = []
for i in range(n):
    turns = []
    for r in range(rng.randint(2, 6)):
        turns.append({"from": "human", "value": text(rng.randint(10, 120))})
        turns.append({"from": "gpt", "value": text(rng.randint(20, 200))})
    data.append({"id": f"synthetic-{i}", "conversations": turns})
with open(out, "w") as f:
    json.dump(data, f)
print(f"wrote {out}: {n} synthetic ShareGPT conversations")
EOF
  exit 0
fi

OUT="${1:-sharegpt.json}"
curl -L -o "$OUT" \
  "https://huggingface.co/datasets/anon8231489123/ShareGPT_Vicuna_unfiltered/resolve/main/ShareGPT_V3_unfiltered_cleaned_split.json"
echo "wrote $OUT"
