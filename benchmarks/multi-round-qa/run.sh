#!/usr/bin/env bash
# QPS sweep of the multi-round-qa benchmark (reference: run.sh — warmup
# then sweep with 320 users / 10 rounds / 1000-token system prompt /
# 20000-token history / 100-token answers). Scale knobs via env.
set -euo pipefail
cd "$(dirname "$0")"

BASE_URL="${BASE_URL:-http://localhost:8001}"
MODEL="${MODEL:?set MODEL}"
USERS="${USERS:-320}"
ROUNDS="${ROUNDS:-10}"
SYS_LEN="${SYS_LEN:-1000}"
HIST_LEN="${HIST_LEN:-20000}"
ANSWER_LEN="${ANSWER_LEN:-100}"
DURATION="${DURATION:-120}"
QPS_SWEEP="${QPS_SWEEP:-1 2 4 8}"
# stagger user starts + hold concurrency constant via session
# recycling (reference multi-round-qa.py ramp-up/recycling semantics)
RAMP="${RAMP:-20}"

echo "== warmup =="
python3 multi_round_qa.py --base-url "$BASE_URL" --model "$MODEL" \
  --num-users "$USERS" --num-rounds 2 --qps 0 \
  --shared-system-prompt-len "$SYS_LEN" --user-history-len "$HIST_LEN" \
  --answer-len 16 --duration 60 --output warmup.json

for qps in $QPS_SWEEP; do
  echo "== qps=$qps =="
  python3 multi_round_qa.py --base-url "$BASE_URL" --model "$MODEL" \
    --num-users "$USERS" --num-rounds "$ROUNDS" --qps "$qps" \
    --shared-system-prompt-len "$SYS_LEN" --user-history-len "$HIST_LEN" \
    --answer-len "$ANSWER_LEN" --duration "$DURATION" \
    --ramp-up-time "$RAMP" --recycle \
    --output "summary_qps${qps}.json"
done

echo "done; summaries in summary_qps*.json"
