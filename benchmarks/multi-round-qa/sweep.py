"""One-command QPS sweep: run multi_round_qa at each QPS, print ONE table.

The round-over-round perf surface (reference: run.sh sweep loop +
manual spreadsheet): each row is one QPS point with completion rate,
throughputs, TTFT and ITL percentiles; results land in --out-dir as
summary_qps*.json (plot.py consumes them) plus sweep.md with the table.

Usage:
  python sweep.py --base-url http://localhost:8001 --model llama-3.2-1b \
      --qps 1 2 4 8 --num-users 32 --duration 60
"""

from __future__ import annotations

import argparse
import json
import os

import multi_round_qa


COLUMNS = [
    ("qps", "achieved QPS"),
    ("requests_completed", "done"),
    ("errors", "errors"),
    ("prompt_throughput_tok_s", "prompt tok/s"),
    ("generation_throughput_tok_s", "gen tok/s"),
    ("avg_ttft_s", "avg TTFT"),
    ("p50_ttft_s", "p50 TTFT"),
    ("p99_ttft_s", "p99 TTFT"),
    ("p50_itl_s", "p50 ITL"),
    ("p99_itl_s", "p99 ITL"),
]


def to_table(rows: list[tuple[float, dict]]) -> str:
    header = ["offered QPS"] + [label for _, label in COLUMNS]
    lines = [
        "| " + " | ".join(header) + " |",
        "|" + "|".join("---" for _ in header) + "|",
    ]
    for qps, s in rows:
        cells = [str(qps)] + [
            "-" if s.get(key) is None else str(s[key]) for key, _ in COLUMNS
        ]
        lines.append("| " + " | ".join(cells) + " |")
    return "\n".join(lines)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--base-url", default="http://localhost:8001")
    p.add_argument("--model", required=True)
    p.add_argument("--qps", type=float, nargs="+", default=[1, 2, 4, 8])
    p.add_argument("--num-users", type=int, default=32)
    p.add_argument("--num-rounds", type=int, default=10)
    p.add_argument("--shared-system-prompt-len", type=int, default=1000)
    p.add_argument("--user-history-len", type=int, default=2000)
    p.add_argument("--answer-len", type=int, default=100)
    p.add_argument("--duration", type=float, default=120.0)
    p.add_argument("--sharegpt-path", default=None)
    p.add_argument("--out-dir", default="sweep-results")
    p.add_argument("--skip-warmup", action="store_true")
    args = p.parse_args(argv)
    os.makedirs(args.out_dir, exist_ok=True)

    def qa_args(qps: float, **over) -> list[str]:
        base = [
            "--base-url", args.base_url, "--model", args.model,
            "--num-users", str(args.num_users),
            "--num-rounds", str(over.get("num_rounds", args.num_rounds)),
            "--qps", str(qps),
            "--shared-system-prompt-len",
            str(args.shared_system_prompt_len),
            "--user-history-len", str(args.user_history_len),
            "--answer-len", str(over.get("answer_len", args.answer_len)),
            "--duration", str(over.get("duration", args.duration)),
        ]
        if args.sharegpt_path:
            base += ["--sharegpt-path", args.sharegpt_path]
        if "output" in over:
            base += ["--output", over["output"]]
        return base

    if not args.skip_warmup:
        print("== warmup (compile buckets, fill prefix cache) ==")
        multi_round_qa.main(
            qa_args(0, num_rounds=2, answer_len=16,
                    duration=min(60.0, args.duration))
        )

    rows: list[tuple[float, dict]] = []
    for qps in args.qps:
        print(f"== qps={qps} ==")
        out = os.path.join(args.out_dir, f"summary_qps{qps}.json")
        rows.append((qps, multi_round_qa.main(qa_args(qps, output=out))))

    table = to_table(rows)
    print("\n" + table)
    with open(os.path.join(args.out_dir, "sweep.md"), "w") as f:
        f.write(table + "\n")
    print(f"\nresults in {args.out_dir}/ (plot: python plot.py --series "
          f"run={args.out_dir} -o {args.out_dir}/sweep.png)")


if __name__ == "__main__":
    main()
