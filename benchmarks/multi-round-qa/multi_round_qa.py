"""Multi-round QA serving benchmark.

Same workload semantics as the reference's headline benchmark (reference:
benchmarks/multi-round-qa/multi-round-qa.py — N concurrent users, M chat
rounds each, target aggregate QPS, shared system prompt + growing per-user
history, streamed answers, TTFT at first chunk; summary QPS / prompt
throughput / generation throughput / average TTFT, :446-518), written
fresh on asyncio+aiohttp instead of the reference's thread/openai-client
design.

Usage:
  python multi_round_qa.py --base-url http://localhost:8001 \
      --model llama-3.2-1b --num-users 32 --num-rounds 10 --qps 2 \
      --shared-system-prompt-len 1000 --user-history-len 2000 \
      --answer-len 100 --duration 120 --output summary.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import string
import sys
import time
from dataclasses import dataclass, field

import aiohttp


def load_sharegpt(path: str, max_conversations: int = 0) -> list[list[dict]]:
    """Load ShareGPT-format conversations -> list of OpenAI message lists.

    Accepts the standard dump format (list of {"conversations":
    [{"from": "human"|"gpt", "value": ...}]}) the reference prepares via
    prepare_sharegpt_data.sh. Conversations are normalized to
    user/assistant turns starting with a user turn.
    """
    with open(path) as f:
        raw = json.load(f)
    out: list[list[dict]] = []
    role_map = {"human": "user", "user": "user",
                "gpt": "assistant", "assistant": "assistant"}
    for item in raw:
        turns = item.get("conversations") or item.get("messages") or []
        msgs: list[dict] = []
        for t in turns:
            role = role_map.get(t.get("from") or t.get("role"))
            text = t.get("value") or t.get("content")
            if role is None or not text:
                continue
            if not msgs and role != "user":
                continue  # drop leading assistant turns
            if msgs and msgs[-1]["role"] == role:
                msgs[-1]["content"] += "\n" + text
                continue
            msgs.append({"role": role, "content": text})
        if len(msgs) >= 2:
            out.append(msgs)
        if max_conversations and len(out) >= max_conversations:
            break
    if not out:
        raise ValueError(f"no usable conversations in {path}")
    return out


def synthetic_text(num_words: int, seed: int) -> str:
    rng = random.Random(seed)
    words = []
    for _ in range(num_words):
        n = rng.randint(3, 9)
        words.append(
            "".join(rng.choices(string.ascii_lowercase, k=n))
        )
    return " ".join(words)


@dataclass
class RequestRecord:
    start: float
    first_token: float | None = None
    end: float | None = None
    prompt_tokens: int = 0
    completion_tokens: int = 0
    ok: bool = False
    itls: list = field(default_factory=list)  # inter-chunk gaps (s)

    @property
    def ttft(self) -> float | None:
        if self.first_token is None:
            return None
        return self.first_token - self.start


@dataclass
class UserSession:
    """One simulated user: rounds of Q->A with history accumulation
    (reference: UserSession state machine, multi-round-qa.py:182)."""

    user_id: int
    args: argparse.Namespace
    history: list[dict] = field(default_factory=list)
    rounds_done: int = 0
    sharegpt_conv: list[dict] | None = None  # this user's conversation

    def build_messages(self) -> list[dict]:
        msgs = [{"role": "system", "content": self.args._system_prompt}]
        if self.sharegpt_conv is not None:
            # replay the real conversation: history so far + next user turn
            user_turn_idx = [
                i for i, m in enumerate(self.sharegpt_conv)
                if m["role"] == "user"
            ]
            k = self.rounds_done % len(user_turn_idx)
            upto = user_turn_idx[k]
            msgs.extend(self.history)
            msgs.append(self.sharegpt_conv[upto])
            return msgs
        if not self.history and self.args.user_history_len > 0:
            # per-user unique context so prefix caching can't collapse users
            self.history.append({
                "role": "user",
                "content": synthetic_text(
                    self.args.user_history_len, seed=self.user_id
                ),
            })
            self.history.append({
                "role": "assistant", "content": "understood.",
            })
        msgs.extend(self.history)
        msgs.append({
            "role": "user",
            "content": (
                f"question {self.rounds_done} from user {self.user_id}: "
                + synthetic_text(24, seed=self.user_id * 1000 +
                                 self.rounds_done)
            ),
        })
        return msgs


class Benchmark:
    def __init__(self, args: argparse.Namespace):
        self.args = args
        self.records: list[RequestRecord] = []
        self.errors = 0
        self._convs = None
        if getattr(args, "sharegpt_path", None):
            self._convs = load_sharegpt(args.sharegpt_path)
        self.sessions = [
            self._new_session(i) for i in range(args.num_users)
        ]
        self._next_user_id = args.num_users
        self.sessions_completed = 0
        # sessions enter the free queue in run(): all at t=0, or
        # staggered over --ramp-up-time (reference's user ramp-up,
        # multi-round-qa.py:386 — a thundering herd at t=0 measures the
        # cold-start queue, not steady-state serving)
        self.free_sessions = asyncio.Queue()

    def _new_session(self, user_id: int) -> UserSession:
        """One construction path for initial AND recycled users, so the
        two populations can't silently diverge."""
        s = UserSession(user_id, self.args)
        if self._convs is not None:
            s.sharegpt_conv = self._convs[user_id % len(self._convs)]
        return s

    async def run_request(self, session: UserSession,
                          http: aiohttp.ClientSession) -> None:
        msgs = session.build_messages()
        rec = RequestRecord(start=time.time())
        body = {
            "model": self.args.model,
            "messages": msgs,
            "max_tokens": self.args.answer_len,
            "temperature": 0.0,
            "stream": True,
            "stream_options": {"include_usage": True},
        }
        answer_parts: list[str] = []
        last_chunk_t = 0.0
        finish_reason: str | None = None
        try:
            async with http.post(
                f"{self.args.base_url}/v1/chat/completions", json=body
            ) as resp:
                if resp.status != 200:
                    self.errors += 1
                    return
                async for raw_line in resp.content:
                    line = raw_line.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == "[DONE]":
                        break
                    try:
                        chunk = json.loads(payload)
                    except json.JSONDecodeError:
                        continue
                    now_chunk = time.time()
                    got_content = False
                    for choice in chunk.get("choices", []):
                        delta = choice.get("delta", {})
                        if delta.get("content"):
                            answer_parts.append(delta["content"])
                            rec.completion_tokens += 1
                            got_content = True
                        fr = choice.get("finish_reason")
                        if fr is not None:
                            finish_reason = fr
                    # TTFT/ITL count CONTENT chunks only: servers send an
                    # eager role-delta chunk before any token is computed,
                    # and error/abort chunks carry no content — timing
                    # those would fabricate sub-millisecond TTFTs
                    if got_content:
                        if rec.first_token is None:
                            rec.first_token = now_chunk
                        else:
                            rec.itls.append(now_chunk - last_chunk_t)
                        last_chunk_t = now_chunk
                    usage = chunk.get("usage")
                    if usage:
                        rec.prompt_tokens = usage.get("prompt_tokens", 0)
                        rec.completion_tokens = usage.get(
                            "completion_tokens", rec.completion_tokens
                        )
            rec.end = time.time()
            if finish_reason not in ("stop", "length") or (
                rec.completion_tokens == 0
            ):
                # aborted/errored streams (e.g. context overflow) are
                # failures, not zero-token completions that would
                # silently zero every latency percentile
                self.errors += 1
                return
            rec.ok = True
            session.history.append({"role": "user",
                                    "content": msgs[-1]["content"]})
            session.history.append({"role": "assistant",
                                    "content": "".join(answer_parts)})
            session.rounds_done += 1
        except (aiohttp.ClientError, asyncio.TimeoutError):
            self.errors += 1
        finally:
            self.records.append(rec)
            if session.rounds_done < self.args.num_rounds:
                self.free_sessions.put_nowait(session)
            else:
                self.sessions_completed += 1
                if self.args.recycle:
                    # session recycling (reference multi-round-qa.py:407):
                    # replace the finished user with a FRESH one so
                    # concurrency holds constant for the whole run
                    fresh = self._new_session(self._next_user_id)
                    self._next_user_id += 1
                    # NOT appended to self.sessions: with recycling on,
                    # nothing reads that list after admission, and keeping
                    # every finished session's full chat history alive
                    # grows memory for the whole run
                    self.free_sessions.put_nowait(fresh)

    async def _admit_sessions(self, t_start: float) -> None:
        """Feed users into the free queue: all at once, or staggered
        over --ramp-up-time."""
        ramp = self.args.ramp_up_time
        if ramp >= self.args.duration:
            # users admitted after the deadline would never run: the
            # sweep point would silently measure lower concurrency than
            # configured
            print(
                f"WARNING: --ramp-up-time {ramp}s >= --duration "
                f"{self.args.duration}s; clamping ramp to "
                f"{self.args.duration / 2:.1f}s so every user runs",
                file=sys.stderr,
            )
            ramp = self.args.duration / 2
        if ramp <= 0:
            for s in self.sessions:
                self.free_sessions.put_nowait(s)
            return
        gap = ramp / max(1, len(self.sessions))
        for i, s in enumerate(list(self.sessions)):
            delay = t_start + i * gap - time.time()
            if delay > 0:
                await asyncio.sleep(delay)
            self.free_sessions.put_nowait(s)

    async def run(self) -> dict:
        timeout = aiohttp.ClientTimeout(total=self.args.request_timeout)
        conn = aiohttp.TCPConnector(limit=0)
        t_start = time.time()
        deadline = t_start + self.args.duration
        interval = 1.0 / self.args.qps if self.args.qps > 0 else 0.0
        pending: set[asyncio.Task] = set()
        launched = 0
        admitter = asyncio.create_task(self._admit_sessions(t_start))
        async with aiohttp.ClientSession(
            timeout=timeout, connector=conn
        ) as http:
            next_fire = time.time()
            while time.time() < deadline:
                if interval:
                    now = time.time()
                    if now < next_fire:
                        await asyncio.sleep(
                            min(next_fire - now, deadline - now)
                        )
                        continue
                    next_fire += interval
                try:
                    sess = self.free_sessions.get_nowait()
                except asyncio.QueueEmpty:
                    # all users busy or finished: exit early when the
                    # whole workload is done (without recycling the run
                    # would otherwise idle to the deadline, inflating
                    # `elapsed` and underreporting qps/throughput)
                    if not self.args.recycle and not pending and all(
                        s.rounds_done >= self.args.num_rounds
                        for s in self.sessions
                    ):
                        break
                    await asyncio.sleep(0.005)
                    continue
                task = asyncio.create_task(self.run_request(sess, http))
                pending.add(task)
                task.add_done_callback(pending.discard)
                launched += 1
            if pending:
                await asyncio.wait(pending, timeout=self.args.request_timeout)
        admitter.cancel()
        elapsed = time.time() - t_start
        return self.summary(elapsed, launched)

    def summary(self, elapsed: float, launched: int) -> dict:
        done = [r for r in self.records if r.ok]
        ttfts = sorted(r.ttft for r in done if r.ttft is not None)
        itls = sorted(g for r in done for g in r.itls)
        prompt_tokens = sum(r.prompt_tokens for r in done)
        gen_tokens = sum(r.completion_tokens for r in done)

        def pct(p):
            if not ttfts:
                return None
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))]

        return {
            "duration_s": round(elapsed, 2),
            "requests_launched": launched,
            "requests_completed": len(done),
            "sessions_completed": self.sessions_completed,
            "errors": self.errors,
            "qps": round(len(done) / elapsed, 3) if elapsed else 0,
            "prompt_throughput_tok_s":
                round(prompt_tokens / elapsed, 1) if elapsed else 0,
            "generation_throughput_tok_s":
                round(gen_tokens / elapsed, 1) if elapsed else 0,
            "avg_ttft_s":
                round(sum(ttfts) / len(ttfts), 4) if ttfts else None,
            "p50_ttft_s": round(pct(0.50), 4) if ttfts else None,
            "p90_ttft_s": round(pct(0.90), 4) if ttfts else None,
            "p99_ttft_s": round(pct(0.99), 4) if ttfts else None,
            "p50_itl_s": round(itls[len(itls) // 2], 4) if itls else None,
            "p90_itl_s":
                round(itls[min(len(itls) - 1, int(0.9 * len(itls)))], 4)
                if itls else None,
            "p99_itl_s":
                round(itls[min(len(itls) - 1, int(0.99 * len(itls)))], 4)
                if itls else None,
        }


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--base-url", default="http://localhost:8001")
    p.add_argument("--model", required=True)
    p.add_argument("--num-users", type=int, default=32)
    p.add_argument("--num-rounds", type=int, default=10)
    p.add_argument("--qps", type=float, default=2.0,
                   help="target aggregate request launch rate; 0 = as "
                        "fast as users free up")
    p.add_argument("--shared-system-prompt-len", type=int, default=1000,
                   help="words in the shared system prompt")
    p.add_argument("--user-history-len", type=int, default=2000,
                   help="words of unique per-user first-round context")
    p.add_argument("--answer-len", type=int, default=100)
    p.add_argument("--duration", type=float, default=120.0)
    p.add_argument("--ramp-up-time", type=float, default=0.0,
                   help="stagger user starts over this many seconds "
                        "instead of a thundering herd at t=0 "
                        "(reference ramp-up, multi-round-qa.py:386)")
    p.add_argument("--recycle", action="store_true",
                   help="replace users that finish their rounds with "
                        "fresh ones so concurrency holds constant "
                        "(reference session recycling, "
                        "multi-round-qa.py:407)")
    p.add_argument("--request-timeout", type=float, default=120.0)
    p.add_argument("--sharegpt-path", default=None,
                   help="ShareGPT-format JSON: replay real conversations "
                        "instead of synthetic text (see "
                        "prepare_sharegpt_data.sh)")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    args._system_prompt = (
        "You are a helpful assistant. "
        + synthetic_text(args.shared_system_prompt_len, seed=42)
    )
    return args


def main(argv=None) -> dict:
    args = parse_args(argv)
    result = asyncio.run(Benchmark(args).run())
    print(json.dumps(result, indent=2))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(result, f, indent=2)
    return result


if __name__ == "__main__":
    main()
