"""Plot QPS-sweep results (role parity with reference plot.py).

Reads the summary_qps*.json files a sweep produces and renders TTFT +
throughput vs offered QPS, one series per labelled directory so two
stacks (e.g. round N vs round N+1, or TPU vs GPU) can be compared.

Usage:
  python plot.py summary_qps*.json -o sweep.png
  python plot.py --series tpu=run_tpu --series a100=run_a100 -o cmp.png
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re


def load_series(paths: list[str]) -> list[tuple[float, dict]]:
    out = []
    for path in paths:
        with open(path) as f:
            summary = json.load(f)
        m = re.search(r"qps(\d+(?:\.\d+)?)", os.path.basename(path))
        qps = float(m.group(1)) if m else summary.get("qps", 0.0)
        out.append((qps, summary))
    out.sort(key=lambda t: t[0])
    return out


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("files", nargs="*", help="summary_qps*.json files")
    p.add_argument("--series", action="append", default=[],
                   help="label=dir with summary_qps*.json inside")
    p.add_argument("-o", "--output", default="sweep.png")
    args = p.parse_args(argv)

    series: dict[str, list[tuple[float, dict]]] = {}
    if args.files:
        series["run"] = load_series(args.files)
    for spec in args.series:
        label, _, d = spec.partition("=")
        series[label] = load_series(
            sorted(glob.glob(os.path.join(d, "summary_qps*.json")))
        )
    if not series:
        raise SystemExit("no input files (pass files or --series)")

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(15, 4.2))
    metrics = [
        ("p50_ttft_s", "p50 TTFT (s)"),
        ("generation_throughput_tok_s", "generation tok/s"),
        ("p50_itl_s", "p50 ITL (s)"),
    ]
    for ax, (key, label) in zip(axes, metrics):
        for name, rows in series.items():
            xs = [q for q, s in rows if s.get(key) is not None]
            ys = [s[key] for _, s in rows if s.get(key) is not None]
            if xs:
                ax.plot(xs, ys, marker="o", label=name)
        ax.set_xlabel("offered QPS")
        ax.set_ylabel(label)
        ax.grid(True, alpha=0.3)
        ax.legend()
    fig.suptitle("multi-round-qa QPS sweep")
    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
