#!/usr/bin/env bash
# Router stress floor (reference: tests/e2e/stress-test.sh — 10,000
# requests at 2,000 concurrency through the router against mock backends;
# asserts even distribution and zero drops). Pure-python load generator
# instead of Apache Bench (not in this image).
set -euo pipefail
cd "$(dirname "$0")/.."

TOTAL="${TOTAL:-10000}"
CONCURRENCY="${CONCURRENCY:-2000}"
python3 - "$TOTAL" "$CONCURRENCY" <<'EOF'
import asyncio, json, sys, time
sys.path.insert(0, ".")
sys.path.insert(0, "tests")

TOTAL, CONCURRENCY = int(sys.argv[1]), int(sys.argv[2])

async def main():
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer
    from fake_engine import FakeEngine
    from production_stack_tpu.router import parsers
    from production_stack_tpu.router.app import build_app

    engines = [FakeEngine(model="m", num_tokens=2) for _ in range(2)]
    for e in engines:
        await e.start()
    args = parsers.parse_args([
        "--service-discovery", "static",
        "--static-backends", ",".join(e.url for e in engines),
        "--static-models", "m,m",
        "--routing-logic", "roundrobin",
    ])
    ra = build_app(args)
    client = TestClient(TestServer(ra.app))
    await client.start_server()

    sem = asyncio.Semaphore(CONCURRENCY)
    ok = 0
    fail = 0

    async def one(i):
        nonlocal ok, fail
        async with sem:
            try:
                r = await client.post("/v1/completions", json={
                    "model": "m", "prompt": f"req {i}", "max_tokens": 2})
                if r.status == 200:
                    ok += 1
                else:
                    fail += 1
                await r.release()
            except Exception:
                fail += 1

    t0 = time.time()
    await asyncio.gather(*(one(i) for i in range(TOTAL)))
    dt = time.time() - t0
    counts = [len(e.requests_seen) for e in engines]
    print(json.dumps({
        "total": TOTAL, "concurrency": CONCURRENCY,
        "ok": ok, "failed": fail, "rps": round(TOTAL / dt, 1),
        "distribution": counts,
    }))
    assert fail == 0, f"{fail} dropped requests"
    assert abs(counts[0] - counts[1]) <= TOTAL * 0.02, (
        f"uneven distribution: {counts}")
    await client.close()
    for e in engines:
        await e.stop()
    print("STRESS TEST PASSED")

asyncio.run(main())
EOF
